"""Graph plan + pure-JAX interpreter + shape/type inference.

Reference parity: this module replaces the nnvm pass machinery the
GraphExecutor drove (`src/executor/graph_executor.cc`): InferShape/InferType
(:597) become incremental `jax.eval_shape` over the plan; PlanMemory /
AttachOpExecs / op bulking are all subsumed by tracing `run()` under one
`jax.jit` (XLA plans memory and fuses).  Parameter-shape hooks reproduce the
reference ops' InferShape for auto-created weights (e.g. FC weight from
num_hidden × flattened data — src/operator/nn/fully_connected-inl.h).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import MXNetError, np_dtype
from .. import layout as _layout
from ..observability import introspect as _introspect
from ..ops import registry as _reg
from ..ops.elemwise import _BINARY as _EW_BINARY, _SCALAR as _EW_SCALAR, \
    _UNARY as _EW_UNARY
from ..ops.sequence import rnn_param_size, _GATES
from .symbol import Symbol, _Node, _truthy


# -- whole-graph channels-last propagation (VERDICT r4 #1b) -----------------
# Per-op boundary transposes (layout.py to_cl/from_cl inside each spatial
# op) measured SLOWER than NCHW on-chip (LAYOUT_r04: framework NHWC 1540
# vs NCHW 1577) even though raw-JAX NHWC wins (1929 vs 1860): XLA does
# not reliably cancel the transpose pairs across conv→BN→relu→conv
# chains once bf16 converts/broadcasts sit between them.  This pass
# moves the layout decision to the GRAPH level: spatial ops exchange
# channels-last values directly (ops/nn.py `__io_layout__`), elementwise
# ops pass the tag through, and a real transpose is materialized only
# where a layout-sensitive consumer (FC, reshape, softmax, ...) or a
# graph output needs NCHW — i.e. at true graph edges.

# ops that are layout-transparent on their single array input
_CL_EW_ONE = (set(_EW_UNARY) | set(_EW_SCALAR) |
              {"Activation", "Dropout", "_copy", "BlockGrad",
               "make_loss", "clip", "Cast", "smooth_l1"})
# binary elemwise: transparent when both inputs have the same shape
_CL_EW_TWO = {"broadcast_" + k for k in _EW_BINARY}


def _prod(xs):
    out = 1
    for x in xs:
        out *= x
    return out


# op name -> fn(params, in_shapes) -> {input_index: shape} for unknown-var fill
def _fc_hook(p, shp):
    d = shp[0]
    red = _prod(d[1:]) if p.get("flatten", True) else d[-1]
    out = {1: (p["num_hidden"], red)}
    if not p.get("no_bias"):
        out[2] = (p["num_hidden"],)
    return out


def _conv_hook(p, shp):
    d = shp[0]
    out = {1: (p["num_filter"], d[1] // p.get("num_group", 1)) + tuple(p["kernel"])}
    if not p.get("no_bias"):
        out[2] = (p["num_filter"],)
    return out


def _deconv_hook(p, shp):
    d = shp[0]
    out = {1: (d[1], p["num_filter"] // p.get("num_group", 1)) + tuple(p["kernel"])}
    if not p.get("no_bias"):
        out[2] = (p["num_filter"],)
    return out


def _bn_hook(p, shp):
    c = shp[0][p.get("axis", 1) % len(shp[0])]
    return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}


def _in_hook(p, shp):
    c = shp[0][1]
    return {1: (c,), 2: (c,)}


def _ln_hook(p, shp):
    c = shp[0][p.get("axis", -1) % len(shp[0])]
    return {1: (c,), 2: (c,)}


def _emb_hook(p, shp):
    return {1: (p["input_dim"], p["output_dim"])}


def _rnn_hook(p, shp):
    T, B, I = shp[0]
    L, H = p["num_layers"], p["state_size"]
    d = 2 if p.get("bidirectional") else 1
    out = {1: (rnn_param_size(L, I, H, bool(p.get("bidirectional")), p["mode"]),),
           2: (L * d, B, H)}
    if p["mode"] == "lstm":
        out[3] = (L * d, B, H)
    return out


def _prelu_hook(p, shp):
    if p.get("act_type") == "prelu" and len(shp) > 1:
        return {1: (shp[0][1] if len(shp[0]) > 1 else shp[0][0],)}
    return {}


def _softmax_output_hook(p, shp):
    d = shp[0]
    if p.get("multi_output"):
        return {1: (d[0],) + tuple(d[2:])}
    if p.get("preserve_shape"):
        return {1: tuple(d[:-1])}
    return {1: (d[0],)}


def _regression_hook(p, shp):
    return {1: tuple(shp[0])}


def _ce_hook(p, shp):
    return {1: (shp[0][0],)}


def _custom_hook(p, shp):
    from ..ops.custom import _custom_shape_hook
    return _custom_shape_hook(p, shp)


PARAM_SHAPE_HOOKS: Dict[str, Callable] = {
    "Custom": _custom_hook,
    "SoftmaxOutput": _softmax_output_hook,
    "LinearRegressionOutput": _regression_hook,
    "LogisticRegressionOutput": _regression_hook,
    "MAERegressionOutput": _regression_hook,
    "SVMOutput": _ce_hook,
    "softmax_cross_entropy": _ce_hook,
    "FullyConnected": _fc_hook,
    "Convolution": _conv_hook,
    "Deconvolution": _deconv_hook,
    "BatchNorm": _bn_hook,
    "InstanceNorm": _in_hook,
    "LayerNorm": _ln_hook,
    "Embedding": _emb_hook,
    "RNN": _rnn_hook,
    "LeakyReLU": _prelu_hook,
}


class _Step:
    __slots__ = ("node", "op", "params", "in_refs", "out_base", "aux_var_names")

    def __init__(self, node, op, params, in_refs, out_base, aux_var_names):
        self.node = node
        self.op = op
        self.params = params      # normalized dict (without __is_train__)
        self.in_refs = in_refs    # list of ('var', name) | ('val', (step_idx, out_idx))
        self.out_base = out_base  # index into the flat value table
        self.aux_var_names = aux_var_names  # input-aux-slot -> var name (or None)


class GraphPlan:
    """Topologically-ordered executable plan for a Symbol."""

    def __init__(self, symbol: Symbol):
        self.symbol = symbol
        nodes = symbol._topo()
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.input_names = set(self.arg_names) | set(self.aux_names)
        node_out: Dict[int, Any] = {}
        self.steps: List[_Step] = []
        for n in nodes:
            if n.is_var:
                node_out[id(n)] = ("var", n.name)
                continue
            op = _reg.get_op(n.op)
            params = dict(op.normalize(_canon_params(op, n, len(n.inputs))))
            in_refs = []
            for src, oi in n.inputs:
                ref = node_out[id(src)]
                if ref[0] == "var":
                    in_refs.append(ref)
                else:
                    in_refs.append(("val", (ref[1], oi)))
            aux_map = {}
            for pos, ai in enumerate(op.aux_inputs):
                if ai < len(n.inputs) and n.inputs[ai][0].is_var:
                    aux_map[pos] = n.inputs[ai][0].name
            step_idx = len(self.steps)
            self.steps.append(_Step(n, op, params, in_refs, step_idx, aux_map))
            node_out[id(n)] = ("step", step_idx)
        # map output entries
        self.out_refs = []
        for node, oi in symbol._entries:
            ref = node_out[id(node)]
            if ref[0] == "var":
                self.out_refs.append(("var", node.name))
            else:
                self.out_refs.append(("val", (ref[1], oi)))

    def out_stypes(self) -> list:
        """Storage type of each graph output: 'row_sparse'/'csr' when the
        producing node is cast_storage with a sparse target, else
        'default'.  The executor wraps such outputs in real sparse
        NDArrays at the graph boundary (parity: cast_storage.cc
        CastStorageComputeEx producing an rsp/csr output chunk — inside
        XLA compute stays dense, the storage class materializes where
        the value leaves the compiled program)."""
        out = []
        for ref in self.out_refs:
            st = "default"
            if ref[0] == "val":
                step = self.steps[ref[1][0]]
                if step.op.name == "cast_storage":
                    st = step.params.get("stype", "default")
            out.append(st if st in ("row_sparse", "csr") else "default")
        return out

    def sparse_grad_args(self) -> Dict[str, list]:
        """Arg names whose gradient the executor can produce ROWS-ONLY:
        variables used exclusively as the weight of
        Embedding(sparse_grad=True) steps whose data input is itself a
        graph input (the Module-API sparse-embedding pattern; parity:
        indexing_op.h rsp EmbeddingOpBackward + infer-storage making the
        weight grad row_sparse).  Returns {name: [(step_idx, data_var)]}.
        """
        users: Dict[str, list] = {}
        for si, s in enumerate(self.steps):
            for pos, ref in enumerate(s.in_refs):
                if ref[0] == "var":
                    users.setdefault(ref[1], []).append((si, s, pos))
        direct_outs = {r[1] for r in self.out_refs if r[0] == "var"}
        out = {}
        for name, us in users.items():
            if name in direct_outs:
                continue
            if all(s.op.name == "Embedding" and pos == 1
                   and bool(s.params.get("sparse_grad"))
                   and s.in_refs[0][0] == "var"
                   for _, s, pos in us):
                out[name] = [(si, s.in_refs[0][1]) for si, s, _ in us]
        return out

    def specialize_init_shapes(self, known_shapes: Dict[str, tuple]) -> None:
        """Resolve 0-dims in init-op shape params (rnn begin_state) against
        the bound arg shapes — the bind-time leg of the candidate
        substitution in infer_shapes_types."""
        if not known_shapes or not any(
                s.op.name in ("_zeros", "_ones", "_full")
                and s.params.get("shape") is not None
                and any(int(d) == 0 for d in s.params["shape"])
                for s in self.steps):
            return
        try:
            plan2, _, _ = infer_shapes_types(
                self.symbol, {k: tuple(v) for k, v in known_shapes.items()
                              if v is not None}, {})
        except MXNetError:
            return
        self.init_overrides = getattr(plan2, "init_overrides", {})
        for si, p in self.init_overrides.items():
            self.steps[si].params.update(p)

    # -- whole-graph channels-last pass --------------------------------
    def _apply_cl(self, step, ins, in_cl, overridden):
        """One step of the layout propagation: given resolved inputs and
        their channels-last tags, return (ins', extra_params, out_cl).
        out_cl tags OUTPUT 0 only (spatial ops' secondary outputs — BN
        saved mean/var — are per-channel vectors, never CL)."""
        name = step.op.name
        p = step.params

        def demote():
            return ([_layout.from_cl(v) if f else v
                     for v, f in zip(ins, in_cl)], None, False)

        if overridden:
            return demote()
        x = ins[0] if ins else None
        nd = getattr(x, "ndim", 0)
        if name in ("Convolution", "Deconvolution"):
            if nd == len(p["kernel"]) + 2:
                out = [_layout.from_cl(v) if f and i else v
                       for i, (v, f) in enumerate(zip(ins, in_cl))]
                if not in_cl[0]:
                    out[0] = _layout.to_cl(x)
                return out, {"__io_layout__": "NHWC"}, True
            return demote()
        if name == "Pooling" and nd >= 3:
            return ([x if in_cl[0] else _layout.to_cl(x)],
                    {"__io_layout__": "NHWC"}, True)
        if name == "BatchNorm" and nd >= 3 and p.get("axis", 1) % nd == 1:
            out = list(ins)
            out[0] = x if in_cl[0] else _layout.to_cl(x)
            return out, {"__io_layout__": "NHWC"}, True
        if name in _CL_EW_ONE and len(ins) == 1:
            if name == "LeakyReLU" and p.get("act_type") == "prelu":
                return demote()
            return list(ins), None, bool(in_cl[0])
        if name in _CL_EW_TWO and len(ins) == 2 and any(in_cl):
            s0, s1 = (getattr(v, "shape", None) for v in ins)
            if s0 is not None and s0 == s1:
                return [v if f else _layout.to_cl(v)
                        for v, f in zip(ins, in_cl)], None, True
        if (name == "Concat" and p.get("dim", 1) == 1 and any(in_cl)
                and all(getattr(v, "ndim", 0) >= 3 for v in ins)):
            # channel-axis concat stays channels-last (the axis moves to
            # the minor position — ops/matrix.py honors __io_layout__);
            # densenet/inception concat chains keep the CL region intact
            return ([v if f else _layout.to_cl(v)
                     for v, f in zip(ins, in_cl)],
                    {"__io_layout__": "NHWC"}, True)
        return demote()

    # -- execution (pure; call under jit) -----------------------------------
    def run(self, arg_values: Dict[str, Any], aux_values: Dict[str, Any],
            key, is_train: bool, step_overrides=None, segments: int = 1):
        """Execute the graph. Returns (outputs, new_aux_values).

        `step_overrides` maps step index -> fn(params, inputs) returning
        the step's output tuple (the executor's rows-only embedding-grad
        rewrite rides this hook).

        `segments > 1` runs the step list as that many contiguous
        `jax.checkpoint` segments: a vjp over the call then saves only
        the segment-boundary live values and recomputes within each
        segment during backprop — sqrt(N) activation memory, the TPU
        redesign of the reference's backward-mirror pass
        (MXNET_BACKWARD_DO_MIRROR, src/executor/graph_executor.cc
        mirror-stage selection).  A whole-graph jax.checkpoint gives no
        saving (the recompute re-materializes every activation at
        once); segmentation is what makes remat pay."""
        if segments and segments > 1 and not step_overrides:
            return self._run_segmented(arg_values, aux_values, key,
                                       is_train, int(segments))
        values: List[Tuple] = [None] * len(self.steps)
        new_aux = dict(aux_values)
        use_cl = _layout.channels_last() and _layout.whole_graph()
        cl_flags: Dict[tuple, bool] = {}

        def resolve(ref):
            if ref[0] == "var":
                nm = ref[1]
                if nm in arg_values:
                    return arg_values[nm]
                if nm in new_aux:
                    return new_aux[nm]
                raise MXNetError(f"unbound variable '{nm}'")
            si, oi = ref[1]
            return values[si][oi]

        def cl_of(ref):
            return ref[0] == "val" and cl_flags.get(ref[1], False)

        for si, step in enumerate(self.steps):
            ins = [resolve(r) for r in step.in_refs]
            if use_cl:
                ins, extra, out_cl = self._apply_cl(
                    step, ins, [cl_of(r) for r in step.in_refs],
                    bool(step_overrides and si in step_overrides))
                cl_flags[(si, 0)] = out_cl
            else:
                extra = None
            p = dict(step.params)
            if extra:
                p.update(extra)
            if step.op.takes_is_train:
                p["__is_train__"] = is_train
            if step.op.needs_rng:
                ins.append(jax.random.fold_in(key, si))
            # layer attribution (ISSUE 13): each step traces under a
            # jax.named_scope of its node name, so HLO instruction
            # metadata carries layer names through forward AND the vjp
            # (introspect.per_layer parses them back out).  Trace-time
            # only — compiled programs pay nothing per execution; one
            # boolean when MXNET_INTROSPECT=0
            with _introspect.layer_scope(step.node.name):
                if step_overrides and si in step_overrides:
                    out = step_overrides[si](p, ins)
                else:
                    out = step.op.fn(p, *ins)
            out = out if isinstance(out, tuple) else (out,)
            n_vis = len(out) - len(step.op.aux_inputs)
            values[si] = out[:n_vis]
            for pos, nm in step.aux_var_names.items():
                new_aux[nm] = out[n_vis + pos]
        outputs = [_layout.from_cl(resolve(r)) if cl_of(r) else resolve(r)
                   for r in self.out_refs]
        return outputs, new_aux

    def _segment_layout(self, k: int):
        """Contiguous segmentation [(b0, b1, live_in_keys), ...] where
        live_in_keys are the (step, out_idx) values produced before b0
        and still consumed at/after b0 (step index len(steps) stands for
        the graph outputs).  Cached per k."""
        cache = self.__dict__.setdefault("_seg_cache", {})
        if k in cache:
            return cache[k]
        n = len(self.steps)
        k = max(1, min(k, n))
        bounds = sorted({int(round(i * n / k)) for i in range(k + 1)})
        consumers: Dict[tuple, list] = {}
        for si, step in enumerate(self.steps):
            for ref in step.in_refs:
                if ref[0] == "val":
                    consumers.setdefault(ref[1], []).append(si)
        for ref in self.out_refs:
            if ref[0] == "val":
                consumers.setdefault(ref[1], []).append(n)
        segs = []
        for b0, b1 in zip(bounds[:-1], bounds[1:]):
            live_in = sorted(key for key, cs in consumers.items()
                             if key[0] < b0 and any(c >= b0 for c in cs))
            segs.append((b0, b1, live_in))
        # live set AFTER the last segment = exactly the output values
        cache[k] = segs
        return segs

    def _run_segmented(self, arg_values, aux_values, key, is_train, k):
        segs = self._segment_layout(k)
        use_cl = _layout.channels_last() and _layout.whole_graph()
        # tags persist across segment traces (values crossing a
        # checkpoint boundary keep their physical layout; the dict is
        # filled in execution order, segment i before i+1)
        cl_flags: Dict[tuple, bool] = {}

        def make_seg(b0, b1, live_out_keys):
            def seg(args, live_in, aux_in, key_):
                local: Dict[tuple, Any] = dict(live_in)
                aux = dict(aux_in)

                def resolve(ref):
                    if ref[0] == "var":
                        nm = ref[1]
                        if nm in args:
                            return args[nm]
                        if nm in aux:
                            return aux[nm]
                        raise MXNetError(f"unbound variable '{nm}'")
                    return local[ref[1]]

                def cl_of(ref):
                    return ref[0] == "val" and cl_flags.get(ref[1], False)

                for si in range(b0, b1):
                    step = self.steps[si]
                    ins = [resolve(r) for r in step.in_refs]
                    if use_cl:
                        ins, extra, out_cl = self._apply_cl(
                            step, ins, [cl_of(r) for r in step.in_refs],
                            False)
                        cl_flags[(si, 0)] = out_cl
                    else:
                        extra = None
                    p = dict(step.params)
                    if extra:
                        p.update(extra)
                    if step.op.takes_is_train:
                        p["__is_train__"] = is_train
                    if step.op.needs_rng:
                        ins.append(jax.random.fold_in(key_, si))
                    with _introspect.layer_scope(step.node.name):
                        out = step.op.fn(p, *ins)
                    out = out if isinstance(out, tuple) else (out,)
                    n_vis = len(out) - len(step.op.aux_inputs)
                    for oi in range(n_vis):
                        local[(si, oi)] = out[oi]
                    for pos, nm in step.aux_var_names.items():
                        aux[nm] = out[n_vis + pos]
                return {kk: local[kk] for kk in live_out_keys}, aux
            return jax.checkpoint(seg)

        live: Dict[tuple, Any] = {}
        aux = dict(aux_values)
        out_keys = sorted({ref[1] for ref in self.out_refs
                           if ref[0] == "val"})
        for i, (b0, b1, _) in enumerate(segs):
            nxt = segs[i + 1][2] if i + 1 < len(segs) else out_keys
            live, aux = make_seg(b0, b1, nxt)(arg_values, live, aux, key)
        outputs = [arg_values[r[1]] if r[0] == "var" and r[1] in arg_values
                   else aux[r[1]] if r[0] == "var"
                   else (_layout.from_cl(live[r[1]])
                         if cl_flags.get(r[1], False) else live[r[1]])
                   for r in self.out_refs]
        return outputs, aux


def _canon_params(op, node, n_inputs):
    p = {}
    for k, v in node.params.items():
        if k in op.schema.args:
            p[k] = v
    if op.variadic and "num_args" in op.schema.args:
        p["num_args"] = n_inputs
    return p


# ---------------------------------------------------------------------------
# shape / type inference
# ---------------------------------------------------------------------------
def _node_eval_shape(op, params, in_structs):
    p = dict(params)
    if op.takes_is_train:
        p["__is_train__"] = False
    args = list(in_structs)
    if op.needs_rng:
        args.append(jax.random.PRNGKey(0))

    def f(*ins):
        out = op.fn(p, *ins)
        return out if isinstance(out, tuple) else (out,)

    return jax.eval_shape(f, *args)


def infer_shapes_types(symbol: Symbol, known_shapes: Dict[str, tuple],
                       known_types: Dict[str, Any], partial: bool = False):
    """Returns ({input_name: (shape, dtype)}, [(shape, dtype) per output]).

    Variables carrying a partial `__shape__` hint with 0-dims (the
    reference's "unknown dim" convention — e.g. RNN begin_state (0, H),
    rnn_cell.py state_info) are resolved by candidate substitution: try
    each dim appearing in the known input shapes for the 0s; a wrong
    candidate fails loudly at the first binary-op shape check, the right
    one completes inference.  This replaces nnvm's bidirectional
    InferShape pass for the begin-state case without a full constraint
    solver.
    """
    plan = GraphPlan(symbol)
    info: Dict[str, Optional[jax.ShapeDtypeStruct]] = {}
    partial_hints: Dict[str, tuple] = {}
    for nm in plan.input_names:
        shp = known_shapes.get(nm)
        node_attr_shape = None
        dt = known_types.get(nm, _np.float32)
        if shp is None:
            # __shape__ attr hint on the variable
            for n in symbol._topo():
                if n.is_var and n.name == nm and "__shape__" in n.attrs:
                    node_attr_shape = eval(n.attrs["__shape__"], {"__builtins__": {}})
            shp = node_attr_shape
        if shp is not None and any(int(d) == 0 for d in shp):
            partial_hints[nm] = tuple(int(d) for d in shp)
            shp = None  # 0-dims mean "unknown" until substitution
        if shp is not None:
            info[nm] = jax.ShapeDtypeStruct(tuple(int(d) for d in shp),
                                            np_dtype(dt))
        else:
            info[nm] = None

    # init ops (_zeros/_ones, e.g. rnn begin_state) with 0-dims in their
    # static shape param are likewise unknown-until-substitution
    partial_steps: Dict[int, tuple] = {}
    for si, step in enumerate(plan.steps):
        shp = step.params.get("shape")
        if step.op.name in ("_zeros", "_ones", "_full") and shp is not None \
                and any(int(d) == 0 for d in shp):
            partial_steps[si] = tuple(int(d) for d in shp)

    if (partial_hints or partial_steps) and known_shapes:
        candidates: List[int] = []
        for s in known_shapes.values():
            for d in s:
                if int(d) > 0 and int(d) not in candidates:
                    candidates.append(int(d))
        # 1 broadcasts against everything, so it can never "fail loudly";
        # try it only after every stricter candidate has been rejected
        if 1 in candidates:
            candidates.remove(1)
            candidates.append(1)
        for c in candidates:
            trial = dict(info)
            for nm, hint in partial_hints.items():
                if trial.get(nm) is None:
                    filled = tuple(c if d == 0 else d for d in hint)
                    trial[nm] = jax.ShapeDtypeStruct(
                        filled, np_dtype(known_types.get(nm, _np.float32)))
            overrides = {si: {"shape": tuple(c if d == 0 else d for d in hint)}
                         for si, hint in partial_steps.items()}
            try:
                res = _infer_forward(plan, symbol, trial, partial=False,
                                     param_overrides=overrides)
            except MXNetError:
                continue
            # record + apply the winning substitution so executors running
            # this plan materialize correctly-sized begin-states
            plan.init_overrides = overrides
            for si, p in overrides.items():
                plan.steps[si].params.update(p)
            return res
    return _infer_forward(plan, symbol, info, partial=partial)


def _infer_forward(plan, symbol, info, partial, param_overrides=None):

    step_out: List[Optional[tuple]] = [None] * len(plan.steps)

    def ref_struct(ref):
        if ref[0] == "var":
            return info.get(ref[1])
        si, oi = ref[1]
        return step_out[si][oi] if step_out[si] is not None else None

    for si, step in enumerate(plan.steps):
        structs = [ref_struct(r) for r in step.in_refs]
        if any(s is None for s in structs):
            hook = PARAM_SHAPE_HOOKS.get(step.op.name)
            if hook is not None and structs[0] is not None:
                fills = hook(step.params, [s.shape if s else None for s in structs])
                for idx, shp in fills.items():
                    if idx < len(structs) and structs[idx] is None:
                        ref = step.in_refs[idx]
                        if ref[0] == "var":
                            st = jax.ShapeDtypeStruct(tuple(int(x) for x in shp),
                                                      structs[0].dtype)
                            info[ref[1]] = st
                            structs[idx] = st
        if any(s is None for s in structs):
            if partial:
                continue
            missing = [step.in_refs[i] for i, s in enumerate(structs) if s is None]
            raise MXNetError(
                f"infer_shape: cannot infer input(s) {missing} of node "
                f"'{step.node.name}' ({step.op.name}); provide their shapes")
        try:
            p = step.params
            if param_overrides and si in param_overrides:
                p = {**p, **param_overrides[si]}
            outs = _node_eval_shape(step.op, p, structs)
        except Exception as e:  # shape error inside op
            raise MXNetError(f"infer_shape failed at node '{step.node.name}' "
                             f"({step.op.name}): {e}") from None
        n_vis = len(outs) - len(step.op.aux_inputs)
        step_out[si] = tuple(outs[:n_vis])

    out_structs = []
    for ref in plan.out_refs:
        out_structs.append(ref_struct(ref))
    return plan, info, out_structs


def infer_shape(symbol: Symbol, partial: bool, *args, **kwargs):
    known = {}
    arg_names = symbol.list_arguments()
    if args:
        for nm, shp in zip(arg_names, args):
            if shp is not None:
                known[nm] = shp
    known.update({k: v for k, v in kwargs.items() if v is not None})
    try:
        plan, info, outs = infer_shapes_types(symbol, known, {}, partial=partial)
    except MXNetError:
        if partial:
            return None, None, None
        raise
    # `is not None`, not truthiness: a scalar output's ShapeDtypeStruct
    # raises on __len__ (loss graphs end in shape-() outputs)
    arg_shapes = [tuple(info[n].shape) if info.get(n) is not None else None
                  for n in arg_names]
    aux_shapes = [tuple(info[n].shape) if info.get(n) is not None else None
                  for n in symbol.list_auxiliary_states()]
    out_shapes = [tuple(o.shape) if o is not None else None for o in outs]
    return arg_shapes, out_shapes, aux_shapes


def _f32_forced_vars(symbol: Symbol):
    """Variables that stay f32 under reduced-precision training — declared
    per-op in the registry (Operator.f32_inputs: BN scale/stats, class-id/
    index inputs)."""
    plan = GraphPlan(symbol)
    forced = set()
    for step in plan.steps:
        for i in step.op.f32_inputs:
            if i < len(step.in_refs) and step.in_refs[i][0] == "var":
                forced.add(step.in_refs[i][1])
    return forced


def infer_type(symbol: Symbol, *args, **kwargs):
    known_t = {}
    arg_names = symbol.list_arguments()
    if args:
        for nm, dt in zip(arg_names, args):
            if dt is not None:
                known_t[nm] = dt
    known_t.update({k: v for k, v in kwargs.items() if v is not None})
    # reference-style propagation: unknown float params take the training
    # dtype — fp16/bf16 data implies fp16/bf16 weights, exactly how
    # reference fp16 training binds — except the registry's f32-forced
    # inputs.  The training dtype = the first known float input in
    # argument (topological) order that is NOT itself f32-forced (so a
    # f32 label never wins the scan over bf16 data, whatever the names).
    forced = _f32_forced_vars(symbol)
    float_default = _np.float32
    for nm in arg_names:
        dt = known_t.get(nm)
        if dt is None or nm in forced:
            continue
        # jnp.issubdtype: bf16/f16 are ml_dtypes, invisible to numpy's
        # floating hierarchy
        if jax.numpy.issubdtype(np_dtype(dt), jax.numpy.floating):
            float_default = np_dtype(dt)
            break
    def var_t(n):
        if n in known_t:
            return np_dtype(known_t[n])
        return _np.dtype(_np.float32) if n in forced else float_default

    arg_types = [var_t(n) for n in arg_names]
    aux_types = [var_t(n) for n in symbol.list_auxiliary_states()]
    out_types = [np_dtype(float_default)] * len(symbol._entries)
    return arg_types, out_types, aux_types
