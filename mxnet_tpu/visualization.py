"""Network visualization (parity: python/mxnet/visualization.py:47,192)."""
from __future__ import annotations

import json

from .base import MXNetError


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Textual layer summary (parity: visualization.print_summary)."""
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        shape_dict = dict(zip(symbol.list_arguments(), arg_shapes))
    else:
        shape_dict = {}
    nodes = symbol._topo()
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    positions = [int(line_length * p) for p in positions]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(header, positions)
    print("=" * line_length)
    total_params = 0
    for node in nodes:
        if node.is_var:
            continue
        op_name = f"{node.name}({node.op})"
        params = 0
        for src, _ in node.inputs:
            if src.is_var and src.name in shape_dict:
                import numpy as np
                if src.name != "data" and not src.name.endswith("label"):
                    params += int(np.prod(shape_dict[src.name]))
        total_params += params
        prev = ",".join(s.name for s, _ in node.inputs)
        print_row([op_name, "", params, prev[:40]], positions)
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot (parity: visualization.plot_network); requires the
    optional graphviz package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires the graphviz python package")
    node_attrs = node_attrs or {}
    dot = Digraph(name=title)
    nodes = symbol._topo()
    for node in nodes:
        if node.is_var:
            if not hide_weights or node.name in ("data",) or \
                    node.name.endswith("label"):
                dot.node(node.name, node.name, shape="oval")
            continue
        dot.node(node.name, f"{node.name}\n{node.op}", shape="box")
        for src, _ in node.inputs:
            if src.is_var and hide_weights and src.name not in ("data",) \
                    and not src.name.endswith("label"):
                continue
            dot.edge(src.name, node.name)
    return dot
