"""Training-loop callbacks.

API parity with the reference frontend (python/mxnet/callback.py):
epoch-end checkpointers (`do_checkpoint`, `module_checkpoint`) and
batch-end loggers (`Speedometer`, `ProgressBar`, `log_train_metric`,
`LogValidationMetricsCallback`).  Implementation is original to this
package: all loggers funnel through `_emit`, periodic triggers share
`_due`, and the two checkpointers share one factory.

Batch-end callbacks receive a BatchEndParam-style object with ``epoch``,
``nbatch`` and ``eval_metric`` attributes (model.py); epoch-end
callbacks are called as ``cb(epoch, symbol, arg_params, aux_params)``.
"""
from __future__ import annotations

import logging
import time


def _emit(fmt, *values):
    logging.info(fmt, *values)


def _due(counter: int, period: int) -> bool:
    """True on every `period`-th 1-indexed tick."""
    return period > 0 and counter % period == 0


def _metric_pairs(param):
    m = getattr(param, "eval_metric", None)
    return m.get_name_value() if m else []


# ---------------------------------------------------------------------------
# Epoch-end: checkpointing
# ---------------------------------------------------------------------------
def _checkpointer(save_fn, period, managed_fn=None):
    """When MXNET_CHECKPOINT_DIR is set (checked at CALL time, so
    long-lived jobs can opt in without re-building callbacks), saves
    route through the fault-tolerant CheckpointManager — async, atomic,
    CRC-validated, retention-GC'd (docs/checkpointing.md).  Unset, the
    legacy prefix-file write runs unchanged."""
    period = max(1, int(period))

    def on_epoch_end(epoch, sym=None, arg=None, aux=None):
        if not _due(epoch + 1, period):
            return
        mgr = None
        if managed_fn is not None:
            from .checkpoint import env_manager
            mgr = env_manager()
        if mgr is not None:
            managed_fn(mgr, epoch + 1, sym, arg, aux)
        else:
            save_fn(epoch + 1, sym, arg, aux)

    return on_epoch_end


def do_checkpoint(prefix, period=1, reference_format=False):
    """Save symbol + params to `prefix`-NNNN.params every `period` epochs
    (reference_format writes the original framework's binary container).
    With MXNET_CHECKPOINT_DIR set, saves go through the atomic
    CheckpointManager instead (epoch number = checkpoint step)."""
    from .model import save_checkpoint

    def _managed(mgr, n, sym, arg, aux):
        from .checkpoint import pack_module_state
        mgr.save(n, pack_module_state(sym, arg or {}, aux or {}),
                 meta={"prefix": prefix, "source": "do_checkpoint"})

    return _checkpointer(
        lambda n, sym, arg, aux: save_checkpoint(
            prefix, n, sym, arg, aux, reference_format=reference_format),
        period, managed_fn=_managed)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Save a Module's checkpoint (and optionally optimizer state) every
    `period` epochs.  With MXNET_CHECKPOINT_DIR set, saves go through
    the atomic CheckpointManager (optimizer state rides along in the
    same atomic commit instead of a second .states file)."""

    def _managed(mgr, n, *_):
        from .checkpoint import pack_module_state
        arg, aux = mod.get_params()
        opt_states = mod.get_optimizer_states_bytes() \
            if save_optimizer_states and mod.optimizer_initialized \
            and hasattr(mod, "get_optimizer_states_bytes") else None
        mgr.save(n, pack_module_state(mod.symbol, arg, aux,
                                      optimizer_states=opt_states),
                 meta={"prefix": prefix, "source": "module_checkpoint"})

    return _checkpointer(
        lambda n, *_: mod.save_checkpoint(prefix, n, save_optimizer_states),
        period, managed_fn=_managed)


# ---------------------------------------------------------------------------
# Batch-end: logging
# ---------------------------------------------------------------------------
def log_train_metric(period, auto_reset=False):
    """Log the training metric every `period` batches."""

    def on_batch_end(param):
        if not _due(param.nbatch, period):
            return
        for name, value in _metric_pairs(param):
            _emit("Iter[%d] Batch[%d] Train-%s=%f",
                  param.epoch, param.nbatch, name, value)
        if auto_reset and param.eval_metric is not None:
            param.eval_metric.reset()

    return on_batch_end


class Speedometer:
    """Throughput logger: samples/sec over each `frequent`-batch stride,
    with the current metric values appended."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._stride_start = None  # wall clock at the stride's first batch
        self._prev_nbatch = -1

    def __call__(self, param):
        if param.nbatch < self._prev_nbatch:
            self._stride_start = None  # new epoch: restart the stride
        self._prev_nbatch = param.nbatch

        if self._stride_start is None:
            self._stride_start = time.time()
            return
        if not _due(param.nbatch, self.frequent):
            return

        elapsed = max(time.time() - self._stride_start, 1e-12)
        rate = self.frequent * self.batch_size / elapsed
        pairs = _metric_pairs(param)
        if pairs:
            if self.auto_reset:
                param.eval_metric.reset()
            tail = "".join("\t%s=%f" % p for p in pairs)
            _emit("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                  param.epoch, param.nbatch, rate, tail)
        else:
            _emit("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                  param.epoch, param.nbatch, rate)
        self._stride_start = time.time()


class ProgressBar:
    """Fixed-width text progress bar over `total` batches."""

    def __init__(self, total, length=80):
        self.total = total
        self.bar_len = length

    def __call__(self, param):
        frac = param.nbatch / float(self.total)
        fill = int(round(self.bar_len * frac))
        pct = int(-(-100.0 * frac // 1))  # ceil
        _emit("[%s] %s%s\r",
              "=" * fill + "-" * (self.bar_len - fill), pct, "%")


class LogValidationMetricsCallback:
    """Epoch-end eval logger: one line per metric."""

    def __call__(self, param):
        for name, value in _metric_pairs(param):
            _emit("Epoch[%d] Validation-%s=%f", param.epoch, name, value)
