"""AttrScope (parity: python/mxnet/attribute.py:24) — with-scope that stamps
attributes (e.g. ctx_group for model parallelism, lr_mult) onto symbols
created inside it."""
from __future__ import annotations

from typing import Dict, Optional

from .base import MXNetError, _ThreadLocalStack


class AttrScope:
    _stack = _ThreadLocalStack()

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise MXNetError("AttrScope values must be strings")
        self._attr = kwargs

    def get(self, attr: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = {}
        for scope in AttrScope._stack.stack:
            merged.update(scope._attr)
        if attr:
            merged.update(attr)
        return merged

    @staticmethod
    def current() -> "AttrScope":
        return AttrScope._stack.top() or _DEFAULT

    def __enter__(self):
        AttrScope._stack.push(self)
        return self

    def __exit__(self, *exc):
        AttrScope._stack.pop()


_DEFAULT = AttrScope()


def current_attrs(attr=None) -> Dict[str, str]:
    merged = {}
    for scope in AttrScope._stack.stack:
        merged.update(scope._attr)
    if attr:
        merged.update(attr)
    return merged
