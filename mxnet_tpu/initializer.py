"""Weight initializers (parity: python/mxnet/initializer.py:53-635).

Full registry: Zero, One, Constant, Uniform, Normal, Orthogonal, Xavier,
MSRAPrelu, Bilinear, LSTMBias, Mixed, per-name InitDesc attr overrides.
"""
from __future__ import annotations

import json
import re
from typing import Optional

import numpy as _np

from .random import host_rng as _host_rng

from .base import MXNetError, Registry

_REG = Registry("initializer")
register = _REG.register


class InitDesc(str):
    """Parameter name + attrs descriptor (parity: initializer.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self) -> str:
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr) -> None:
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_attr = desc.attrs.get("__init__")
        if init_attr:
            create(init_attr)._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif "moving_mean" in name or "running_mean" in name \
                or "moving_avg" in name:
            self._init_zero(desc, arr)
        elif "moving_var" in name or "running_var" in name:
            self._init_one(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # helpers write via arr[:] so they work on NDArray
    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


_REG._map["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


_REG._map["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        arr[:] = _host_rng.uniform(-self.scale, self.scale, arr.shape)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        arr[:] = _host_rng.normal(0.0, self.sigma, arr.shape)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _host_rng.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _host_rng.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(f"Xavier requires ndim>=2, got {shape} for {desc}")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _host_rng.uniform(-scale, scale, shape)
        else:
            arr[:] = _host_rng.normal(0, scale, shape)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = _np.zeros(int(_np.prod(arr.shape)), dtype="float32")
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, rest 0 (parity: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape, dtype="float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_default = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize a fused-RNN packed parameter vector by delegating to an
    inner initializer (parity: initializer.FusedRNN)."""

    def __init__(self, init, num_hidden, num_layers, mode, bidirectional=False,
                 forget_bias=1.0):
        if isinstance(init, str):
            init = create(init)
        super().__init__(init=init.dumps() if init else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._mode = mode

    def _init_weight(self, desc, arr):
        self._init(InitDesc(str(desc).replace("parameters", "weight")), arr)

    _init_default = _init_weight


class Mixed:
    """Pattern-matched initializer dispatch (parity: initializer.Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches parameter {name}")


class Load:
    """Initialize from saved dict of arrays (parity: initializer.Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            arr[:] = self.param[name]
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError(f"parameter {name} not found in loaded params")


def create(name, *args, **kwargs) -> Initializer:
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str) and name.startswith("["):
        cls_name, kw = json.loads(name)
        return _REG.get(cls_name)(**kw)
    return _REG.get(name)(*args, **kwargs)


registry = _REG


class init:
    """`mx.init.*` alias namespace (parity: mxnet.initializer as mx.init)."""
    Initializer = Initializer
    InitDesc = InitDesc
    Zero = Zero
    One = One
    Constant = Constant
    Uniform = Uniform
    Normal = Normal
    Orthogonal = Orthogonal
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Mixed = Mixed
    Load = Load
