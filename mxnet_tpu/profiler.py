"""Profiler (parity: python/mxnet/profiler.py over src/engine/profiler.cc).

Now a façade over `mxnet_tpu.observability`: the span API
(`observability.tracing.trace_span`) and the runtime metrics registry
(`observability.metrics`) feed the same two timelines this module owns —

  - python side: a Chrome-trace event buffer (`_events`) of eager op
    invokes and `trace_span` scopes, dumped by `dump_profile()`;
  - device side: the XLA xplane trace — `profiler_set_state('run')`
    starts `jax.profiler.start_trace` (viewable in TensorBoard/Perfetto);
    spans emit `jax.profiler.TraceAnnotation` so both line up.

The MXNet parity API is unchanged: `set_config`/`set_state`/
`dump_profile`/`pause`/`resume`, plus the MXNET_PROFILER_AUTOSTART env
(initialize.cc parity).  `pause()` only SUPPRESSES recording
(MXProfilePause parity) — previously recorded events survive a
pause/resume cycle; only a stop→run transition clears the buffer.
`dump_profile()` writes atomically (tmp + os.replace) so a crash
mid-dump never leaves a truncated trace file.
"""
from __future__ import annotations

import atexit
import json
import os
import time
from typing import List, Optional

from .base import getenv

_config = {"profile_all": False, "profile_symbolic": True,
           "profile_imperative": False, "profile_memory": False,
           "profile_api": False, "filename": "profile.json"}
_state = "stop"
_paused = False
_events: List[dict] = []
_trace_dir: Optional[str] = None


def profiler_set_config(mode="symbolic", filename="profile.json", **kwargs):
    """Parity: MXSetProfilerConfig (c_api.cc:100)."""
    _config["filename"] = filename
    _config["profile_all"] = mode == "all"
    _config.update(kwargs)


set_config = profiler_set_config


def profiler_set_state(state="stop"):
    """Parity: MXSetProfilerState — 'run' starts tracing, 'stop' ends it.

    Only the stop→run transition clears the event buffer and opens a
    fresh xplane trace dir; pause()/resume() never pass through here
    (MXProfilePause parity: pause suppresses, it does not restart)."""
    global _state, _trace_dir, _paused
    if state == "run" and _state != "run":
        _trace_dir = os.path.splitext(_config["filename"])[0] + "_xplane"
        try:
            import jax
            jax.profiler.start_trace(_trace_dir)
        except Exception:
            _trace_dir = None
        _events.clear()
        _paused = False
    elif state == "run":
        # run->run: at minimum un-pause (scripts written against the old
        # pause()==stop behavior call set_state('run') to resume)
        _paused = False
    elif state == "stop" and _state == "run":
        _stop_trace()
    _state = state


set_state = profiler_set_state


def _stop_trace():
    global _trace_dir
    if _trace_dir is not None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None


def record_event(name: str, start_us: float, end_us: float, cat="operator",
                 tid: int = 0, args: Optional[dict] = None):
    """Timeline hook: eager op invokes and `trace_span` scopes land here
    as Chrome-trace complete events (suppressed while paused)."""
    if _state == "run" and not _paused:
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": start_us, "dur": end_us - start_us,
              "pid": 0, "tid": tid}
        if args:
            ev["args"] = args
        _events.append(ev)


def is_running() -> bool:
    """Parity: profiler state is 'run' (paused still counts as running)."""
    return _state == "run"


def is_recording() -> bool:
    """True when events should actually be recorded: running AND not
    paused — the predicate every hot-path hook tests first."""
    return _state == "run" and not _paused


def dump_profile():
    """Parity: MXDumpProfile — write chrome-trace JSON of python-side
    events (device-side detail lives in the xplane trace directory).
    Atomic via the same ``base.atomic_write`` policy the flight
    recorder's dumps use: a crash mid-dump leaves the previous file
    intact, never a truncated/invalid JSON."""
    global _state
    from .base import atomic_write
    _stop_trace()
    _state = "stop"
    atomic_write(_config["filename"],
                 json.dumps({"traceEvents": _events,
                             "displayTimeUnit": "ms"}))


def pause():
    """Parity: MXProfilePause — suppress recording, keep everything
    already recorded (and keep the profiler formally 'running')."""
    global _paused
    _paused = True


def resume():
    """Parity: MXProfileResume — recording continues; previously
    recorded events are preserved."""
    global _paused
    _paused = False


# -- observability façade -----------------------------------------------------
# The span API and metrics exporters live in mxnet_tpu.observability;
# re-exported here so profiler-era user code finds the whole toolkit in
# one namespace (mx.profiler.trace_span(...), mx.profiler.dump_metrics()).
def trace_span(name: str, cat: str = "runtime"):
    from .observability.tracing import trace_span as _ts
    return _ts(name, cat=cat)


def step_span(step_num: int, name: str = "train"):
    from .observability.tracing import step_span as _ss
    return _ss(step_num, name=name)


def dump_metrics() -> dict:
    """Snapshot of the runtime metrics registry (dispatch counts,
    transfer bytes, data-wait, HBM) — see observability.metrics."""
    from .observability import metrics as _m
    return _m.snapshot()


def phase_span(name: str, cat: str = "phase", **kw):
    """Flight-recorder phase span (observability.flight) — always-on
    ring recording, independent of the profiler state."""
    from .observability.flight import phase_span as _ps
    return _ps(name, cat=cat, **kw)


def dump_flight(path=None):
    """Dump the flight-recorder ring (merged with this profiler's
    `_events`) as Perfetto-loadable Chrome trace JSON."""
    from .observability.flight import dump as _dump
    return _dump(path)


if getenv("MXNET_PROFILER_AUTOSTART", 0):
    profiler_set_config(mode="all", filename="profile_output.json")
    profiler_set_state("run")
    atexit.register(dump_profile)
