"""Profiler (parity: python/mxnet/profiler.py over src/engine/profiler.cc).

The reference recorded per-operator exec stats in the engine and dumped
Chrome-trace JSON.  On TPU, XLA/PJRT profiling is the native mechanism:
`profiler_set_state('run')` starts a jax profiler trace (xplane, viewable in
TensorBoard/Perfetto and convertible to chrome trace); `dump_profile()` stops
it.  The MXNET_PROFILER_AUTOSTART env var is honored (initialize.cc parity).
Additionally a lightweight python-side op timeline records eager op invokes
and can be dumped as chrome-trace JSON to `filename` for API parity.
"""
from __future__ import annotations

import atexit
import json
import os
import time
from typing import List, Optional

from .base import getenv

_config = {"profile_all": False, "profile_symbolic": True,
           "profile_imperative": False, "profile_memory": False,
           "profile_api": False, "filename": "profile.json"}
_state = "stop"
_events: List[dict] = []
_trace_dir: Optional[str] = None


def profiler_set_config(mode="symbolic", filename="profile.json", **kwargs):
    """Parity: MXSetProfilerConfig (c_api.cc:100)."""
    _config["filename"] = filename
    _config["profile_all"] = mode == "all"
    _config.update(kwargs)


set_config = profiler_set_config


def profiler_set_state(state="stop"):
    """Parity: MXSetProfilerState — 'run' starts tracing, 'stop' ends it."""
    global _state, _trace_dir
    if state == "run" and _state != "run":
        _trace_dir = os.path.splitext(_config["filename"])[0] + "_xplane"
        try:
            import jax
            jax.profiler.start_trace(_trace_dir)
        except Exception:
            _trace_dir = None
        _events.clear()
    elif state == "stop" and _state == "run":
        _stop_trace()
    _state = state


set_state = profiler_set_state


def _stop_trace():
    global _trace_dir
    if _trace_dir is not None:
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass
        _trace_dir = None


def record_event(name: str, start_us: float, end_us: float, cat="operator"):
    """Engine hook: eager invokes call this when profiling is on."""
    if _state == "run":
        _events.append({"name": name, "cat": cat, "ph": "X",
                        "ts": start_us, "dur": end_us - start_us,
                        "pid": 0, "tid": 0})


def is_running() -> bool:
    return _state == "run"


def dump_profile():
    """Parity: MXDumpProfile — write chrome-trace JSON of python-side events
    (device-side detail lives in the xplane trace directory)."""
    global _state
    _stop_trace()
    _state = "stop"
    with open(_config["filename"], "w") as f:
        json.dump({"traceEvents": _events,
                   "displayTimeUnit": "ms"}, f)


def pause():
    profiler_set_state("stop")


def resume():
    profiler_set_state("run")


if getenv("MXNET_PROFILER_AUTOSTART", 0):
    profiler_set_config(mode="all", filename="profile_output.json")
    profiler_set_state("run")
    atexit.register(dump_profile)
