"""Monitor: tap intermediate outputs during training (parity:
python/mxnet/monitor.py:33 over the executor monitor callback,
src/executor/graph_executor.cc:123,1441)."""
from __future__ import annotations

import logging
import re
from math import sqrt

from . import ndarray as nd
from .ndarray import NDArray


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def asum_stat(x):
                return nd.norm(x) / sqrt(x.size)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        # parity: monitor.py Monitor(monitor_all=...) — record stats for
        # executor inputs as well as outputs
        self.monitor_all = bool(monitor_all)

    def stat_helper(self, name, array):
        if not self.activated or not self.re_prog.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper,
                                 monitor_all=self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            for exe in self.exes:
                for array in exe.arg_dict.values():
                    array.wait_to_read()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        for exe in self.exes:
            for array in exe.arg_dict.values():
                array.wait_to_read()
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                self.stat_helper(name, array)
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if not isinstance(v_list, list):
                v_list = [v_list]
            s = ",".join(str(v.asscalar() if isinstance(v, NDArray) else v)
                         for v in v_list)
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
