"""Standalone inference predictor.

Reference parity: the C predict API (`include/mxnet/c_predict_api.h:78-179`
MXPredCreate/SetInput/Forward/GetOutput and `src/c_api/c_predict_api.cc`) —
a deployment surface that loads a serialized symbol + params and runs
forward-only.  TPU-native realization: the graph compiles once under
`jax.jit` at the requested batch shape; repeated `forward()` calls hit the
cached XLA executable (the amalgamation/mobile role is covered by AOT
compilation through `jax.jit(...).lower(...).compile()`).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol as sym_mod
from .context import Context, cpu


def load_param_payload(params) -> Dict[str, NDArray]:
    """Normalize a param payload to {name: NDArray}.

    Accepts a ready dict (NDArray or numpy values), a serialized blob
    as bytes — parsed IN MEMORY via `nd.load_frombuffer` (MXPredCreate
    takes the blob by pointer; the old tempfile write/unlink round trip
    put a disk write on the model-load path) — or a file path."""
    if isinstance(params, dict):
        return {k: v if isinstance(v, NDArray) else nd.array(v)
                for k, v in params.items()}
    if isinstance(params, (bytes, bytearray, memoryview)):
        loaded = nd.load_frombuffer(bytes(params))
    else:
        loaded = nd.load(params)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "param payload must carry named arrays (arg:/aux: prefixes "
            "or plain names); got an unnamed list")
    return loaded


def split_arg_aux(params: Dict[str, NDArray]):
    """Split a loaded param dict on the `arg:`/`aux:` save prefixes
    (unprefixed names count as args, matching MXPredCreate)."""
    arg_params, aux_params = {}, {}
    for k, v in params.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


class Predictor:
    """Parity: MXPredCreate → the handle; methods mirror the C calls."""

    def __init__(self, symbol_json: str, param_bytes_or_file,
                 input_shapes: Dict[str, tuple], dev=None,
                 output_names: Optional[Sequence[str]] = None):
        symbol = sym_mod.load_json(symbol_json)
        if output_names:
            internals = symbol.get_internals()
            symbol = sym_mod.Group([internals[n] for n in output_names])
        self._symbol = symbol
        self._ctx = dev or cpu()
        arg_params, aux_params = split_arg_aux(
            load_param_payload(param_bytes_or_file))

        arg_names = symbol.list_arguments()
        self._input_names = [n for n in arg_names if n not in arg_params]
        # MXPredCreate copies the param blob onto the requested device
        # (c_predict_api.cc) — loaded params live on the default/CPU
        # context here, so place them before binding
        args = {k: v.as_in_context(self._ctx) for k, v in arg_params.items()}
        aux_params = {k: v.as_in_context(self._ctx)
                      for k, v in aux_params.items()}
        for name, shp in input_shapes.items():
            args[name] = nd.zeros(shp, ctx=self._ctx)
        missing = [n for n in self._input_names if n not in input_shapes]
        if missing:
            # label inputs of training symbols (SoftmaxOutput et al.) get
            # inferred zero placeholders — c_predict_api binds only the
            # data inputs (c_predict_api.cc creates aux zero arrays)
            arg_shapes, _, _ = symbol.infer_shape_partial(**input_shapes)
            inferred = dict(zip(arg_names, arg_shapes or []))
            for name in missing:
                shp = inferred.get(name)
                if shp is None:
                    raise MXNetError(
                        f"input '{name}' requires a shape (MXPredCreate "
                        f"input_shapes parity)")
                args[name] = nd.zeros(shp, ctx=self._ctx)
        self._exec = symbol.bind(
            self._ctx, args=args, args_grad=None, grad_req="null",
            aux_states=aux_params)
        self._outputs: List[NDArray] = []

    # -- C-api-shaped methods ------------------------------------------------
    def set_input(self, name: str, data) -> None:
        """MXPredSetInput."""
        if name not in self._input_names:
            raise MXNetError(f"unknown input '{name}'; inputs: "
                             f"{self._input_names}")
        # host/CPU-built input fed to an accelerator-bound predictor
        # (MXPredSetInput memcpys host->device in the reference);
        # numpy goes straight to the target device (one transfer),
        # copyto owns the dtype-cast + placement rule
        arr = data if isinstance(data, NDArray) \
            else nd.array(data, ctx=self._ctx)
        tgt = self._exec.arg_dict[name]
        if arr.shape != tgt.shape:
            # the C API hands over flat buffers (MXTPredSetInput passes
            # element count only); accept any size-matching layout and
            # fail loudly otherwise — a silent shape swap poisons the
            # bound executor (the reference validates size the same way)
            if arr.size != tgt.size:
                raise MXNetError(
                    f"set_input('{name}'): got {arr.size} elements, "
                    f"expected {tgt.size} {tgt.shape}")
            arr = arr.reshape(tgt.shape)
        arr.copyto(tgt)

    def forward(self) -> None:
        """MXPredForward."""
        self._outputs = self._exec.forward(is_train=False)

    def get_output(self, index: int = 0) -> _np.ndarray:
        """MXPredGetOutput — returns host numpy (the C API memcpy)."""
        if not self._outputs:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index].asnumpy()

    @property
    def num_outputs(self) -> int:
        return len(self._symbol.list_outputs())

    def reshape(self, new_input_shapes: Dict[str, tuple]) -> "Predictor":
        """MXPredReshape: new executor at the new shapes, params shared."""
        for name, shp in new_input_shapes.items():
            self._exec.arg_dict[name] = nd.zeros(shp, ctx=self._ctx)
        self._exec = self._symbol.bind(
            self._ctx, args=self._exec.arg_dict, args_grad=None,
            grad_req="null", aux_states=self._exec.aux_dict)
        return self


def create(symbol_file: str, param_file: str,
           input_shapes: Dict[str, tuple], dev=None) -> Predictor:
    """Parity: MXPredCreate from files (prefix-symbol.json + prefix.params)."""
    with open(symbol_file) as f:
        symbol_json = f.read()
    return Predictor(symbol_json, param_file, input_shapes, dev)
