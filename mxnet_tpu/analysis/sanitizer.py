"""Runtime concurrency sanitizer: lock-order graph + no-sync regions.

The dynamic half of graft-lint (ISSUE 7).  The static thread-safety
checker proves lock DISCIPLINE per class; whether two subsystems'
locks compose without deadlock is a runtime property — so, under
``MXNET_SANITIZE=1``, every lock the package creates through this
module's factories is wrapped to:

  * record a **lock-order graph**: an edge A→B whenever a thread
    acquires B while holding A (aggregated by lock NAME, so two
    instances of the same subsystem count as one node — an ABBA
    inversion across instances is the same hazard);
  * detect **cycles** in that graph at edge-insert time and **raise**
    ``LockOrderError`` (``MXNET_SANITIZE_RAISE=0`` records instead) —
    the test run fails at the moment the second half of a potential
    deadlock is exhibited, with both acquisition stacks in hand;
  * detect **same-thread re-acquisition of a non-reentrant lock** —
    the PR 5 class: a SIGTERM handler re-entering
    ``CheckpointManager`` mid-critical-section.  Without the
    sanitizer this hangs forever; with it, the test fails typed.

It also arms ``no_sync()`` regions: inside ``with analysis.no_sync():``
any device→host synchronization the package performs
(``NDArray.asnumpy``, ``engine.wait_for_var/wait_for_all``) raises
``SyncViolation`` — the runtime complement of the host-sync static
rule, used by the dispatch-count and chaos tests.

Overhead discipline (the repo rule set by the metrics layer): with the
sanitizer off — the default; ``bench.py`` asserts it — the factories
return PLAIN ``threading`` primitives, so production hot paths pay
zero wrapper overhead.  Enable before constructing the objects under
test (``MXNET_SANITIZE=1`` at import covers the whole process).

Results surface through the metrics registry:
``observability.snapshot()["analysis"]``.
"""
from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..base import MXNetError, getenv

__all__ = ["ENABLED", "enable", "disable", "enabled", "sanitized",
           "make_lock", "make_rlock", "make_condition", "no_sync",
           "check_sync", "hot_path", "LockOrderError", "SyncViolation",
           "DonatedBufferError", "poison_donated", "poison_mapping",
           "lock_graph", "violations", "reset", "state"]

# read once at import; enable()/disable() flip it at runtime (tests).
# NOT MXNET_SANITIZE_RAISE-style tolerant parsing by accident: bool
# default routes through base.getenv's "0"/"false"/"" handling.
ENABLED: bool = getenv("MXNET_SANITIZE", False)
RAISE: bool = getenv("MXNET_SANITIZE_RAISE", True)


class LockOrderError(MXNetError):
    """The sanitizer observed a lock-order cycle or a guaranteed
    same-thread deadlock (non-reentrant re-acquisition)."""


class SyncViolation(MXNetError):
    """A device→host synchronization happened inside a ``no_sync()``
    region."""


class DonatedBufferError(MXNetError):
    """A buffer consumed by a donated XLA dispatch was accessed
    afterwards (ISSUE 15's runtime twin of the ``use-after-donate``
    static rule).  Without the sanitizer jax reports this as an opaque
    ``RuntimeError: Array has been deleted`` at some arbitrary later
    access; under ``MXNET_SANITIZE=1`` the wholestep / fused-update /
    serving dispatch boundaries poison the donated wrappers on a failed
    dispatch, so the first touch fails HERE, typed, naming the dispatch
    site — and a snapshot restore (``_set_data`` / ``_load_init``)
    clears the poison exactly like it revives the real buffers."""


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


@contextmanager
def sanitized():
    """Enable for a scope (tests): locks CREATED inside are tracked."""
    global ENABLED
    prev = ENABLED
    ENABLED = True
    try:
        yield
    finally:
        ENABLED = prev


# -- global sanitizer state ---------------------------------------------------
# the graph's own lock is a PLAIN primitive on purpose: tracking the
# tracker would recurse
_STATE_LOCK = threading.Lock()
_EDGES: Dict[Tuple[str, str], dict] = {}   # (from, to) -> {count, stack}
_VIOLATIONS: List[dict] = []
_MAX_VIOLATIONS = 256

_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def lock_graph() -> Dict[Tuple[str, str], int]:
    with _STATE_LOCK:
        return {k: v["count"] for k, v in _EDGES.items()}


def violations() -> List[dict]:
    with _STATE_LOCK:
        return list(_VIOLATIONS)


def reset() -> None:
    """Clear the graph + violation log (NOT per-thread held sets —
    those empty themselves as locks release)."""
    with _STATE_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()


def state() -> dict:
    """The snapshot() payload: JSON-able summary of sanitizer state."""
    with _STATE_LOCK:
        cycles = sum(1 for v in _VIOLATIONS if v["kind"] == "cycle")
        reentry = sum(1 for v in _VIOLATIONS if v["kind"] == "reentry")
        sync = sum(1 for v in _VIOLATIONS if v["kind"] == "sync")
        donated = sum(1 for v in _VIOLATIONS if v["kind"] == "donated")
        return {"enabled": ENABLED, "lock_edges": len(_EDGES),
                "cycles": cycles, "reentry": reentry,
                "sync_violations": sync, "donated_poisoned": donated,
                "violations": [
                    {k: v[k] for k in ("kind", "detail")}
                    for v in _VIOLATIONS[:16]]}


def _record_violation(kind: str, detail: str, extra: Optional[dict] = None,
                      do_raise: bool = True) -> None:
    with _STATE_LOCK:
        if len(_VIOLATIONS) < _MAX_VIOLATIONS:
            entry = {"kind": kind, "detail": detail,
                     "stack": traceback.format_stack(limit=12)}
            if extra:
                entry.update(extra)
            _VIOLATIONS.append(entry)
    try:  # lazy: metrics imports this module's factories at its import
        from ..observability import metrics as _m
        if _m.ENABLED:
            if kind == "sync":
                _m.ANALYSIS_SYNC_VIOLATIONS.inc()
            else:
                _m.ANALYSIS_LOCK_VIOLATIONS.inc(kind=kind)
    except Exception:  # noqa: BLE001 — sanitizer must not crash the host
        pass
    if do_raise and RAISE:
        raise LockOrderError(f"sanitizer: {kind}: {detail}") \
            if kind != "sync" else SyncViolation(detail)


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS src→dst in the name graph.  Caller holds _STATE_LOCK."""
    stack, seen = [(src, [src])], {src}
    adj: Dict[str, list] = {}
    for a, b in _EDGES:
        adj.setdefault(a, []).append(b)
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _on_acquire(lock: "_TrackedLock") -> None:
    """Pre-acquire bookkeeping: re-entry + ordering edges + cycles."""
    held = _held()
    for h, _n in held:
        if h is lock:
            if lock.reentrant:
                return  # legal recursion; no new edges
            _record_violation(
                "reentry",
                f"non-reentrant lock '{lock.name}' re-acquired by the "
                f"thread already holding it (held: "
                f"{[n for _, n in held]}) — this acquire would "
                f"deadlock forever")
            # MXNET_SANITIZE_RAISE=0 only records; the acquire below
            # then genuinely hangs (that IS the bug being recorded)
            return
    for h, hname in held:
        if hname == lock.name:
            continue  # same lock class (two instances): not an order edge
        edge = (hname, lock.name)
        with _STATE_LOCK:
            known = edge in _EDGES
            if not known:
                # cycle check BEFORE inserting: a path to→from plus
                # this edge closes a loop
                path = _find_path(lock.name, hname)
                _EDGES[edge] = {"count": 1,
                                "stack": traceback.format_stack(limit=8)}
            else:
                _EDGES[edge]["count"] += 1
                path = None
        if not known and path is not None:
            cycle = " -> ".join(path + [lock.name])
            _record_violation(
                "cycle",
                f"lock-order cycle: acquiring '{lock.name}' while "
                f"holding '{hname}', but an established order already "
                f"goes {cycle} — ABBA deadlock hazard",
                extra={"cycle": path + [lock.name]})


class _TrackedLock:
    """Wrapper around threading.Lock/RLock that feeds the lock-order
    graph.  Implements the ``_release_save``/``_acquire_restore``/
    ``_is_owned`` trio so ``threading.Condition`` composes (wait()
    fully releases, including RLock recursion)."""

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self.reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()

    # -- core protocol -------------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if ENABLED:
            _on_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got and ENABLED:
            _held().append((self, self.name))
        return got

    def release(self):
        self._inner.release()
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else self._is_owned()

    # -- Condition compatibility --------------------------------------------
    def _release_save(self):
        held = _held()
        removed = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                removed += 1
        if self.reentrant:
            return (self._inner._release_save(), removed)
        self._inner.release()
        return (None, removed)

    def _acquire_restore(self, saved):
        inner_state, removed = saved
        if self.reentrant:
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        if ENABLED:
            _held().extend([(self, self.name)] * max(1, removed))

    def _is_owned(self):
        if self.reentrant:
            return self._inner._is_owned()
        # plain-Lock heuristic (what threading.Condition itself does)
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def make_lock(name: str):
    """A mutex for package subsystems: plain ``threading.Lock`` when
    the sanitizer is off (zero overhead), tracked when on.  ``name``
    is the lock-order graph node (one per subsystem role)."""
    if ENABLED:
        return _TrackedLock(name, reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    if ENABLED:
        return _TrackedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str, reentrant: bool = True):
    """A ``threading.Condition`` whose underlying lock is tracked.
    Reentrant (RLock-backed) by default — matching what a bare
    ``threading.Condition()`` gives you — so signal handlers /
    reentrant callers may re-enter the critical section
    (Condition.wait still fully releases; threading handles the
    recursion count via _release_save).  ``reentrant=False`` opts into
    a plain-Lock condition, which the sanitizer then treats as a
    re-entry deadlock hazard."""
    if ENABLED:
        return threading.Condition(_TrackedLock(name, reentrant))
    return threading.Condition(threading.RLock() if reentrant
                               else threading.Lock())


# -- no-sync regions ----------------------------------------------------------
@contextmanager
def no_sync(label: str = "no_sync"):
    """Assert no device→host synchronization happens in this region
    (armed only under the sanitizer; a no-op otherwise, so hot loops
    may keep the region in production code)."""
    if not ENABLED:
        yield
        return
    depth = getattr(_tls, "no_sync", 0)
    prev_label = getattr(_tls, "no_sync_label", None)
    _tls.no_sync = depth + 1
    _tls.no_sync_label = label
    try:
        yield
    finally:
        _tls.no_sync = depth
        _tls.no_sync_label = prev_label  # outer region keeps ITS label


def check_sync(what: str) -> None:
    """Called by the package's sync chokepoints (NDArray.asnumpy,
    engine waits).  One module-flag test when the sanitizer is off."""
    if not ENABLED:
        return
    if getattr(_tls, "no_sync", 0) > 0:
        label = getattr(_tls, "no_sync_label", "no_sync")
        _record_violation(
            "sync",
            f"device->host sync '{what}' inside no_sync region "
            f"'{label}' — the hot path this region protects just "
            f"gained a blocking host read")


# -- donated-buffer poisoning (ISSUE 15) --------------------------------------
class _DonatedBuffer:
    """Sentinel installed as an NDArray's ``_data`` after a failed
    donated dispatch: ANY use — attribute access (``.shape``,
    ``.dtype``, jax protocols), ``__array__``, truthiness, iteration —
    raises the typed ``DonatedBufferError`` instead of jax's opaque
    deleted-array RuntimeError.  ``repr`` stays safe so debuggers and
    log formatting never explode."""

    __slots__ = ("site", "desc")

    def __init__(self, site: str, desc: str):
        object.__setattr__(self, "site", site)
        object.__setattr__(self, "desc", desc)

    def _raise(self):
        raise DonatedBufferError(
            f"buffer ({self.desc}) was donated to the failed "
            f"'{self.site}' dispatch and may already be consumed by "
            f"XLA — restore it from a host copy "
            f"(TrainingSupervisor snapshot / checkpoint / readmit) "
            f"before reusing it")

    def __getattr__(self, name):
        self._raise()

    def __array__(self, *a, **k):
        self._raise()

    def __bool__(self):
        self._raise()

    def __len__(self):
        self._raise()

    def __iter__(self):
        self._raise()

    def __repr__(self):
        return f"<donated buffer ({self.desc}) consumed by {self.site}>"


def _poison_one(obj, site: str) -> int:
    """Poison one NDArray-like wrapper (tuples/lists/dicts recurse);
    raw jax arrays and None are skipped — only python wrappers can
    carry the sentinel."""
    if obj is None:
        return 0
    if isinstance(obj, (tuple, list)):
        return sum(_poison_one(o, site) for o in obj)
    if isinstance(obj, dict):
        return sum(_poison_one(o, site) for o in obj.values())
    data = getattr(obj, "_data", None)
    if data is None or isinstance(data, _DonatedBuffer) or \
            not hasattr(obj, "_set_data"):
        return 0
    desc = "array"
    try:
        desc = f"{data.dtype}{tuple(data.shape)}"
    except Exception:  # noqa: BLE001 — already-deleted jax arrays
        pass
    # direct rebind, NOT _set_data: the setter would hand the sentinel
    # to engine.maybe_sync.  The next _set_data/_load_init (writeback or
    # snapshot restore) replaces the sentinel and the wrapper is live
    # again — poison clears exactly where the real buffer revives.
    obj._data = _DonatedBuffer(site, desc)
    return 1


def poison_donated(site: str, *wrappers) -> int:
    """Mark NDArray wrappers whose buffers a FAILED donated dispatch
    may have consumed (call from the except path of a donating
    dispatch).  One module-flag test when the sanitizer is off; returns
    the number of wrappers poisoned.  Never raises — it runs while the
    real dispatch error is propagating."""
    if not ENABLED:
        return 0
    try:
        n = sum(_poison_one(w, site) for w in wrappers)
    except Exception:  # noqa: BLE001 — sanitizer must not mask the error
        return 0
    if n:
        _record_violation(
            "donated",
            f"{n} buffer(s) donated to failed '{site}' dispatch were "
            f"poisoned — any access before a restore raises "
            f"DonatedBufferError", do_raise=False)
    return n


def poison_mapping(site: str, mapping: dict) -> int:
    """The serving-boundary variant: replace a dispatch's donated
    input dict values with sentinels IN PLACE, so a retry that
    erroneously reuses the same padded batch fails typed instead of
    serving deleted arrays."""
    if not ENABLED or not isinstance(mapping, dict):
        return 0
    n = 0
    for k, v in list(mapping.items()):
        if isinstance(v, _DonatedBuffer):
            continue
        desc = "array"
        try:
            desc = f"{v.dtype}{tuple(v.shape)}"
        except Exception:  # noqa: BLE001
            pass
        mapping[k] = _DonatedBuffer(site, desc)
        n += 1
    if n:
        _record_violation(
            "donated",
            f"{n} donated input buffer(s) of failed '{site}' dispatch "
            f"were poisoned in place", do_raise=False)
    return n


# -- hot-path marker ----------------------------------------------------------
def hot_path(fn):
    """Mark a function as a dispatch-critical hot path.  Zero runtime
    cost — the marker is consumed by the static host-sync checker
    (mxnet_tpu/analysis/checkers.py), which flags any device→host
    sync reachable from a marked function."""
    fn.__graft_hot_path__ = True
    return fn
