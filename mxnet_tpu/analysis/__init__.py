"""graft-lint: repo-specific static analysis, compiled-program contract
audit, and runtime sanitizer (ISSUEs 7 + 15; docs/static_analysis.md).

Static side — ``analysis.run(checkers, paths) -> [Finding]`` with ten
repo-specific rules: the PR 7 set (thread-safety, host-sync,
atomic-write, env-sync, metrics-hygiene, memory-hygiene) plus the
jit/program-boundary tier (use-after-donate — a def-use dataflow pass
over donated call positions, ``analysis/dataflow.py``; retrace-hazard;
gate-hygiene; bench-emit).  Per-finding ``# graft-lint:
disable=<rule>`` suppression and a checked-in ``baseline.json`` for
grandfathered findings.  ``make lint-graft`` / ``python -m
mxnet_tpu.analysis`` is the CI gate; tests/test_analysis.py pins it in
tier-1.

Program side — ``analysis.audit_programs()`` verifies each captured
compiled program (``observability.introspect``) against the contract
its compile chokepoint declared: donation really became input-output
aliasing, AMP left no f32 dot/conv, zero host callbacks in whole-step
programs, collective count matches the bucketer's plan
(``analysis/program_audit.py``; the CLI's ``--audit-programs`` leg).

Runtime side — ``MXNET_SANITIZE=1`` arms lock-order tracking on every
package lock (deadlock detector), ``no_sync()`` regions that raise on
device→host syncs, and donated-buffer poisoning: a failed donated
dispatch (wholestep / fused-update / serving) marks its wrappers so
any later access raises a typed ``DonatedBufferError`` instead of
jax's opaque deleted-array error; results surface in
``observability.snapshot()["analysis"]``.

This module stays import-light: the whole package imports it for
``hot_path`` / lock factories, so the ast machinery loads lazily.
"""
from __future__ import annotations

from . import sanitizer
from .sanitizer import (DonatedBufferError, LockOrderError, SyncViolation,
                        check_sync, hot_path, make_condition, make_lock,
                        make_rlock, no_sync, sanitized)

__all__ = ["run", "run_detailed", "Finding", "Baseline", "ALL_RULES",
           "hot_path", "no_sync", "sanitizer", "sanitized",
           "make_lock", "make_rlock", "make_condition", "check_sync",
           "LockOrderError", "SyncViolation", "DonatedBufferError",
           "audit_programs", "audit_program"]

_LAZY = {"run": "core", "run_detailed": "core", "Finding": "core",
         "Baseline": "core", "DEFAULT_BASELINE": "core",
         "ALL_RULES": "checkers", "registry": "checkers",
         "audit_programs": "program_audit",
         "audit_program": "program_audit",
         "self_audit": "program_audit"}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
