"""graft-lint: repo-specific static analysis + runtime concurrency
sanitizer (ISSUE 7; docs/static_analysis.md).

Static side — ``analysis.run(checkers, paths) -> [Finding]`` with five
repo-specific rules (thread-safety, host-sync, atomic-write, env-sync,
metrics-hygiene), per-finding ``# graft-lint: disable=<rule>``
suppression and a checked-in ``baseline.json`` for grandfathered
findings.  ``make lint-graft`` / ``python -m mxnet_tpu.analysis`` is
the CI gate; tests/test_analysis.py pins it in tier-1.

Runtime side — ``MXNET_SANITIZE=1`` arms lock-order tracking on every
package lock (deadlock detector) and ``no_sync()`` regions that raise
on device→host syncs; results surface in
``observability.snapshot()["analysis"]``.

This module stays import-light: the whole package imports it for
``hot_path`` / lock factories, so the ast machinery loads lazily.
"""
from __future__ import annotations

from . import sanitizer
from .sanitizer import (LockOrderError, SyncViolation, check_sync,
                        hot_path, make_condition, make_lock, make_rlock,
                        no_sync, sanitized)

__all__ = ["run", "run_detailed", "Finding", "Baseline", "ALL_RULES",
           "hot_path", "no_sync", "sanitizer", "sanitized",
           "make_lock", "make_rlock", "make_condition", "check_sync",
           "LockOrderError", "SyncViolation"]

_LAZY = {"run": "core", "run_detailed": "core", "Finding": "core",
         "Baseline": "core", "DEFAULT_BASELINE": "core",
         "ALL_RULES": "checkers", "registry": "checkers"}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}")
    import importlib
    return getattr(importlib.import_module(f".{mod}", __name__), name)
