"""Compiled-program contract auditor (ISSUE 15).

PR 10 collapsed training into ONE donated XLA program; PR 13 taught the
repo to capture each compiled program's cost/memory/HLO through
``note_program``.  This module closes the loop the TPU-MLIR line argues
for (arxiv 2210.15016): verify the LOWERED artifact against the
contract the call site declared, instead of trusting that the compiler
did what the python-side flags asked.  The four contracts, each born
from a real incident class:

  * **donation → aliasing** — ``donate_argnums`` is a *request*; only
    the HLO header's ``input_output_alias`` table proves the buffers
    really alias (a donation that silently degraded to copy doubles
    the model's HBM footprint — the PR 14 transient-copy class, and
    the premise of every donation-safety rule in checkers.py);
  * **AMP cast coverage** — an ``MXNET_AMP=bf16|fp16`` program must
    contain no f32 ``dot``/``convolution`` (a cast leak silently trains
    full-precision while reporting AMP — no error, wrong perf);
  * **host callbacks** — a whole-step program must contain ZERO
    ``xla_python_*_callback`` custom-calls / infeed / outfeed: one host
    callback turns the 1-dispatch step into a blocking host round trip
    per step;
  * **collective count / plan** — a replicated program must contain
    the bucketer's exact count (0 on the single-process inline reduce;
    a surprise collective means the program is waiting on a mesh
    nobody set up); a GSPMD-sharded program (ISSUE 18) instead
    declares ``mesh_axes`` + ``collective_plan`` and every sized mesh
    axis must carry at least the planned number of XLA-inserted
    collectives — verified by each collective's replica-group span —
    with donation STILL aliased under sharding.

Contracts are declared at the compile chokepoints
(``note_program(..., contracts={...})`` — wholestep, FusedUpdater) and
verified here from the opt-in captured HLO text
(``MXNET_INTROSPECT_HLO=1`` / ``introspect.configure(hlo=True)`` must
be on before the program compiles).  Programs without a contract are
skipped, programs with a contract but no HLO are reported as
``skipped`` (or fail under ``strict=True`` — the CI self-audit mode).

Surfaces: ``analysis.audit_programs()``, the
``python -m mxnet_tpu.analysis --audit-programs`` CLI leg (runs a tiny
whole-step workload so the audit has a real program to chew on — wired
into ``make lint-graft``), and the ``program_audit`` pytest fixture
(tests/conftest.py) that lets dispatch-count tests pin aliasing on the
same program their 1-dispatch gate measures.
"""
from __future__ import annotations

import re
import time
from typing import Dict, List, Optional

__all__ = ["audit_programs", "audit_program", "parse_alias_table",
           "count_host_callbacks", "count_collectives",
           "collective_groups", "amp_cast_coverage", "self_audit"]

# the HLO module header carries the alias table:
#   input_output_alias={ {0}: (0, {}, may-alias), {1}: (3, {}, ...) }
# NESTED braces ({0} output indices, {} param sub-indices) rule out a
# regex over the table — the extent is found by brace counting
_ALIAS_ENTRY_RE = re.compile(r"\{[0-9, ]*\}:\s*\((\d+)")

# instruction shape shared with introspect's flops parser
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.+?)\s+"
                       r"([\w\-]+)\(")

_CALLBACK_TARGETS = ("xla_python_cpu_callback", "xla_python_gpu_callback",
                     "xla_ffi_python_cpu_callback",
                     "xla_ffi_python_gpu_callback", "tf_host_callback")
_HOST_OPS = frozenset({"infeed", "outfeed", "send", "recv"})

_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-reduce-start", "all-gather", "all-gather-start",
    "reduce-scatter", "all-to-all", "collective-permute",
    "collective-permute-start",
})


def parse_alias_table(hlo: str) -> List[int]:
    """Parameter numbers that alias an output, from the module header.
    The header is line 1 of ``as_text()`` so HLO truncation
    (HLO_CAP_BYTES) never loses it."""
    head = hlo.split("\n", 1)[0]
    marker = "input_output_alias={"
    idx = head.find(marker)
    if idx < 0:
        return []
    start = idx + len(marker)
    depth, i = 1, start
    while i < len(head) and depth:
        if head[i] == "{":
            depth += 1
        elif head[i] == "}":
            depth -= 1
        i += 1
    return [int(g) for g in _ALIAS_ENTRY_RE.findall(head[start:i - 1])]


def _instructions(hlo: str):
    for line in hlo.splitlines():
        m = _INSTR_RE.match(line)
        if m is not None:
            yield line, m.group(1), m.group(2)


def count_host_callbacks(hlo: str) -> int:
    n = 0
    for line, _t, op in _instructions(hlo):
        if op == "custom-call" and \
                any(t in line for t in _CALLBACK_TARGETS):
            n += 1
        elif op in _HOST_OPS:
            n += 1
    return n


def count_collectives(hlo: str) -> int:
    return sum(1 for _l, _t, op in _instructions(hlo)
               if op in _COLLECTIVE_OPS)


# iota-form replica groups: `replica_groups=[G,S]<=[...]` — shape is
# [num_groups, group_size], so the span is the SECOND dimension
_RG_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


def collective_groups(hlo: str) -> List[Optional[int]]:
    """One entry per collective instruction: the replica-group SPAN
    (participants per group), or None when the attribute is absent or
    empty — both mean every device participates.  Handles the explicit
    form ``replica_groups={{0,2},{1,3}}`` (span = first subgroup's
    element count; GSPMD emits equal-sized groups) and the iota form
    ``replica_groups=[G,S]<=[...]`` (span = S)."""
    out: List[Optional[int]] = []
    for line, _t, op in _instructions(hlo):
        if op not in _COLLECTIVE_OPS:
            continue
        m = _RG_IOTA_RE.search(line)
        if m is not None:
            out.append(int(m.group(2)))
            continue
        marker = "replica_groups={"
        idx = line.find(marker)
        if idx < 0:
            out.append(None)
            continue
        start = idx + len(marker)
        depth, i = 1, start
        while i < len(line) and depth:
            if line[i] == "{":
                depth += 1
            elif line[i] == "}":
                depth -= 1
            i += 1
        body = line[start:i - 1].strip()
        if not body:
            out.append(None)
            continue
        first = body.lstrip("{").split("}", 1)[0]
        ids = [s for s in first.split(",") if s.strip()]
        out.append(len(ids) if ids else None)
    return out


# computation header: `%fused_computation.3 (p: f32[4]) -> bf16[4] {`
# or `ENTRY %main.90 (...) -> (...) {`
_COMP_HEADER_RE = re.compile(r"^\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(.*->.*\{")
_CALLS_RE = re.compile(r"calls=%([\w.\-]+)")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_DEF_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=")


def amp_cast_coverage(hlo: str, lp: str) -> dict:
    """{"lp": n, "f32": n, "coverage": 0..1} over dot/convolution
    instructions.  ``lp`` is the declared low-precision dtype
    ("bf16"/"fp16" -> HLO "bf16"/"f16").

    A dot/conv counts as CAST-COVERED when its result type is the lp
    dtype (the TPU shape: the MXU really runs low-precision), or when
    an operand carries the lp rounding — defined with an lp type, by a
    ``convert`` touching lp, or by a fusion whose called computation
    contains lp values.  The fusion hop matters on CPU: XLA legalizes
    a bf16 dot as convert(f32→bf16→f32) fusions feeding an f32 dot, so
    the OPTIMIZED text shows f32 dots whose numerics are nonetheless
    bf16-rounded — the contract holds; only a dot with NO lp anywhere
    upstream of its line is a genuine cast leak."""
    want = {"bf16": "bf16", "fp16": "f16"}[lp]
    # computation name -> does its body mention the lp dtype at all
    comp_has_lp: Dict[str, bool] = {}
    cur: Optional[str] = None
    # instruction name -> its defining line (all computations pooled:
    # instruction names are module-unique in HLO text)
    def_line: Dict[str, str] = {}
    for line in hlo.splitlines():
        m = _COMP_HEADER_RE.match(line)
        if m is not None:
            cur = m.group(1)
            comp_has_lp.setdefault(cur, False)
        if cur is not None and f"{want}[" in line:
            comp_has_lp[cur] = True
        dm = _DEF_RE.match(line)
        if dm is not None:
            def_line[dm.group(1)] = line

    def covered(line: str, opcode: str, type_str: str) -> bool:
        if type_str.lstrip().startswith(want):
            return True
        seg = line.split(opcode + "(", 1)
        if len(seg) < 2:
            return False
        body = seg[1].split(" metadata=")[0]
        for op_name in _OPERAND_NAME_RE.findall(body):
            dl = def_line.get(op_name)
            if dl is None:
                continue
            if f"{want}[" in dl:
                return True
            cm = _CALLS_RE.search(dl)
            if cm is not None and comp_has_lp.get(cm.group(1)):
                return True
        return False

    n_lp = n_f32 = 0
    for line, type_str, op in _instructions(hlo):
        if op not in ("dot", "convolution"):
            continue
        if covered(line, op, type_str):
            n_lp += 1
        else:
            n_f32 += 1
    total = n_lp + n_f32
    return {"lp": n_lp, "f32": n_f32,
            "coverage": (n_lp / total) if total else 1.0}


def audit_program(rec: dict) -> List[dict]:
    """Verify one ``introspect.programs()`` record against its declared
    contracts.  Returns issue dicts ``{program, check, ok, detail}`` —
    one per failed check (empty = clean).  A record without contracts
    yields nothing; a contract without captured HLO yields one
    ``hlo-missing`` issue marked ``skipped=True`` so callers can decide
    strictness."""
    contracts = rec.get("contracts")
    if not contracts:
        return []
    name = rec.get("name", "?")
    hlo = rec.get("hlo")
    if not hlo:
        return [{"program": name, "check": "hlo-missing", "ok": False,
                 "skipped": True,
                 "detail": "contract declared but no HLO captured — "
                           "set MXNET_INTROSPECT_HLO=1 (or "
                           "introspect.configure(hlo=True)) before the "
                           "program compiles"}]
    issues: List[dict] = []

    leaves = contracts.get("donated_leaves")
    if leaves is not None:
        aliased = parse_alias_table(hlo)
        if leaves > 0 and len(aliased) < leaves:
            issues.append({
                "program": name, "check": "donation-aliasing",
                "ok": False,
                "detail": f"{leaves} leaves were donated "
                          f"(donate_argnums="
                          f"{contracts.get('donate_argnums')}) but only "
                          f"{len(aliased)} parameter(s) alias an output "
                          f"in the lowered program — the difference is "
                          f"a silent extra copy of those buffers "
                          f"(donation degraded to copy)"})

    amp = contracts.get("amp")
    if amp in ("bf16", "fp16"):
        cov = amp_cast_coverage(hlo, amp)
        allowed = contracts.get("amp_f32_allowed", 0)
        if cov["f32"] > allowed:
            issues.append({
                "program": name, "check": "amp-cast-coverage",
                "ok": False,
                "detail": f"MXNET_AMP={amp} program contains "
                          f"{cov['f32']} f32 dot/conv op(s) "
                          f"(coverage {cov['coverage']:.2%}, allowed "
                          f"f32 count {allowed}) — a cast leak trains "
                          f"full precision while reporting AMP"})

    want_cb = contracts.get("host_callbacks")
    if want_cb is not None:
        got = count_host_callbacks(hlo)
        if got != want_cb:
            issues.append({
                "program": name, "check": "host-callbacks", "ok": False,
                "detail": f"{got} host callback op(s) in the lowered "
                          f"program, contract says {want_cb} — each one "
                          f"is a blocking host round trip inside the "
                          f"compiled step"})

    want_coll = contracts.get("collectives")
    if want_coll is not None:
        got = count_collectives(hlo)
        if got != want_coll:
            issues.append({
                "program": name, "check": "collective-count",
                "ok": False,
                "detail": f"{got} collective op(s) in the lowered "
                          f"program, the bucketer's plan says "
                          f"{want_coll} — the program's communication "
                          f"does not match what was planned"})

    plan = contracts.get("collective_plan")
    if plan:
        # the sharded-program contract: each sized mesh axis must carry
        # at least the planned number of GSPMD collectives.  A
        # collective is credited to an axis when its replica-group span
        # equals the axis size, or when it spans the whole mesh (a
        # fused cross-axis reduce serves every axis it covers); an
        # absent/empty replica_groups spans everything too.
        axes = contracts.get("mesh_axes") or {}
        spans = collective_groups(hlo)
        total = 1
        for v in axes.values():
            total *= int(v)
        for axis, want_min in sorted(plan.items()):
            asize = int(axes.get(axis, 0))
            got = sum(1 for s in spans
                      if s is None or s == asize
                      or (total > 1 and s == total))
            if got < int(want_min):
                issues.append({
                    "program": name, "check": "collective-plan",
                    "ok": False,
                    "detail": f"mesh axis {axis!r} (size {asize}) "
                              f"carries {got} collective(s) in the "
                              f"lowered program, the GSPMD plan "
                              f"requires >= {want_min} — XLA did not "
                              f"insert the cross-shard communication "
                              f"this axis needs (spans seen: "
                              f"{sorted({x for x in spans if x}) or '[]'}"
                              f", {len(spans)} total)"})
    return issues


def audit_programs(programs: Optional[Dict[str, dict]] = None,
                   strict: bool = False) -> dict:
    """Audit every captured program with a declared contract.

    Returns ``{"checked": n, "skipped": [names], "issues": [...],
    "ok": bool, "seconds": s}``.  ``skipped`` are contracts that could
    not be verified (no HLO captured); under ``strict=True`` they count
    as failures — the CI self-audit runs strict because IT controls HLO
    capture."""
    t0 = time.perf_counter()
    if programs is None:
        from ..observability import introspect as _introspect
        programs = _introspect.programs()
    issues: List[dict] = []
    skipped: List[str] = []
    checked = 0
    for name, rec in sorted(programs.items()):
        if not rec.get("contracts"):
            continue
        rec = dict(rec, name=rec.get("name", name))
        out = audit_program(rec)
        if any(i.get("skipped") for i in out):
            skipped.append(name)
            if strict:
                issues.extend(out)
            continue
        checked += 1
        issues.extend(out)
    return {"checked": checked, "skipped": skipped, "issues": issues,
            "ok": not issues,
            "seconds": round(time.perf_counter() - t0, 3)}


# -- the CLI self-audit workload ----------------------------------------------
def self_audit(steps: int = 2, amp: Optional[str] = None) -> dict:
    """Build a tiny whole-step training program WITH HLO capture and
    audit it — the ``--audit-programs`` CLI leg (and the bench lint
    rider's audit half).  Runs entirely in-process on whatever backend
    ``jax`` resolves (the Makefile pins cpu); restores every knob it
    touches.  Returns the ``audit_programs(strict=True)`` report plus
    ``{"programs": [names audited]}``."""
    import os
    import numpy as _np

    from ..observability import introspect as _introspect

    env_prev = {k: os.environ.get(k)
                for k in ("MXNET_WHOLE_STEP", "MXNET_AMP")}
    os.environ["MXNET_WHOLE_STEP"] = "1"
    if amp:
        os.environ["MXNET_AMP"] = amp
    else:
        os.environ.pop("MXNET_AMP", None)
    hlo_prev = _introspect.HLO
    enabled_prev = _introspect.ENABLED
    # the probe notes its program under the canonical "whole_step" name
    # — snapshot the registry so a host process's own captured programs
    # (bench riders, a live trainer) come back untouched
    with _introspect._lock:
        saved_programs = {k: dict(v)
                          for k, v in _introspect._programs.items()}
    _introspect.enable()
    _introspect.configure(hlo=True)
    try:
        from .. import gluon, nd
        from ..gluon.wholestep import WholeStepCompiler

        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dense(8))
        net.initialize()
        loss_fn = gluon.loss.L2Loss()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01, "momentum": 0.9})
        stepper = WholeStepCompiler(net, loss_fn, trainer)
        rs = _np.random.RandomState(0)
        x = nd.array(rs.normal(0, 1, (4, 8)).astype(_np.float32))
        y = nd.array(rs.normal(0, 1, (4, 8)).astype(_np.float32))
        for _ in range(max(1, steps)):
            stepper.step(x, y)
        if not stepper.active:
            return {"checked": 0, "skipped": [], "ok": False,
                    "seconds": 0.0, "programs": [],
                    "issues": [{"program": "whole_step",
                                "check": "build", "ok": False,
                                "detail": "whole-step probe fell back: "
                                          f"{stepper.fallback_reason}"}]}
        progs = {k: v for k, v in _introspect.programs().items()
                 if v.get("contracts")}
        report = audit_programs(progs, strict=True)
        report["programs"] = sorted(progs)
        return report
    finally:
        _introspect.configure(hlo=hlo_prev)
        if not enabled_prev:
            _introspect.disable()
        with _introspect._lock:
            _introspect._programs.clear()
            _introspect._programs.update(saved_programs)
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
