"""Intra-function dataflow for the jit/program-boundary rules (ISSUE 15).

PR 7's checkers are pattern matchers: they recognize a bad call shape
wherever it appears.  The bug class that dominates reviews since PR 10
is different — it is about VALUE LIFETIME across a donated dispatch:
``fn = jax.jit(step, donate_argnums=(0,))`` consumes its argument
buffers, so any later read of a value that flowed through a donated
position is a use of a deleted array (jax raises an opaque
"Array has been deleted" at some arbitrary later point; the PR 10/12/14
incidents).  Catching that statically needs def-use tracking, not
pattern matching — this module is the small dataflow layer the
``use-after-donate`` checker (checkers.py) runs per function.

Scope and honesty: the analysis is INTRA-function and name-based
(dotted ``self.attr`` chains count as names).  It recognizes this
repo's donation idioms:

  * direct construction: ``fn = jax.jit(f, donate_argnums=(0, 2))``;
  * factory methods: a same-file function whose ``return`` is such a
    ``jax.jit`` call (``WholeStepCompiler._build_fn``) makes every
    ``fn = self._build_fn(...)`` a donating callable;
  * the program cache: ``fn = upd.lookup_program(key, lambda:
    self._build_fn(...))`` resolves through the factory argument;
  * conditional donation (``donate_argnums=(0,) if flag else ()``)
    counts as donating — the hazard exists whenever it CAN donate.

A call through a donating callable marks the names passed at donated
positions as dead.  Kills (the value is live again): rebinding the
name, ``del``, and the supervisor/wholestep restore idioms — a call to
``*restore*`` / ``_load_init`` / ``set_states_bytes`` / ``readmit``
/ ``_set_data`` rebuilds state from host copies, so every donated name
is revived (the donation-safe-retry pattern PR 12 shipped); and the
scatter-update restore idiom ``x = x.at[ids].set(...)`` (ISSUE 20's
whole-step embedding update) — the RHS read of ``x`` is NOT a flagged
use because the same statement rebinds ``x`` to the functional result,
which is exactly how a donated table flows through an in-program
scatter and comes out aliased.  Branches
merge conservatively (donated in either arm stays donated; killed only
when killed in both); loop bodies run twice so an un-rebound name
donated at the bottom of an iteration is caught when the next
iteration reads it.

A miss is recoverable (the MXNET_SANITIZE runtime twin raises a typed
``DonatedBufferError`` at the access), a false-positive storm kills
the gate — same conservatism contract as every graft-lint rule.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

__all__ = ["call_name", "donate_argnums_of", "donating_factories",
           "analyze_donation", "DonatedUse"]

#: call names that construct a jit program
_JIT_NAMES = ("jax.jit", "_jax.jit", "jit")

#: a call to one of these (by terminal name, or containing this token)
#: rebuilds state from host copies — every donated name is live again
_RESTORE_TOKENS = ("restore",)
_RESTORE_NAMES = ("_load_init", "set_states_bytes", "readmit",
                  "_set_data", "_init_residuals")

#: ``.at[...]`` scatter methods whose self-rebinding form is the
#: scatter-update restore idiom (see _scatter_restore_root)
_SCATTER_METHODS = ("set", "add", "mul", "multiply", "divide",
                    "min", "max", "power", "apply")


def _scatter_restore_root(expr) -> Optional[ast.AST]:
    """``x = x.at[ids].set(v)`` — jax's functional in-place update, and
    the whole-step embedding scatter (ISSUE 20).  When the single
    assignment target is the same name as the buffer under ``.at``, the
    statement REBINDS the name to the functional result, so the RHS
    read must not be flagged as a use of the donated value (the rebind
    is what lets a donated table flow through the scatter and stay
    aliased).  Returns the read root (the ``x`` under ``.at``) when the
    expression is such a scatter call, else None; the caller checks the
    target-name match."""
    if not isinstance(expr, ast.Call):
        return None
    f = expr.func
    if not (isinstance(f, ast.Attribute) and f.attr in _SCATTER_METHODS):
        return None
    sub = f.value
    if not isinstance(sub, ast.Subscript):
        return None
    at = sub.value
    if not (isinstance(at, ast.Attribute) and at.attr == "at"):
        return None
    return at.value if isinstance(at.value, (ast.Name, ast.Attribute)) \
        else None


def call_name(node: ast.AST) -> str:
    """Dotted name of a call target: ``jax.jit`` -> 'jax.jit'."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _const_argnums(node) -> Optional[Tuple[int, ...]]:
    """A donate_argnums value -> tuple of ints, None if not constant."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def donate_argnums_of(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions of a ``jax.jit(...)`` call, or None when the
    call is not a jit construction / donates nothing.  A conditional
    ``(0,) if flag else ()`` yields the union of both arms — the
    hazard exists whenever the callable CAN donate."""
    if call_name(call.func) not in _JIT_NAMES:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.IfExp):
            a = _const_argnums(v.body) or ()
            b = _const_argnums(v.orelse) or ()
            merged = tuple(sorted(set(a) | set(b)))
            return merged or None
        nums = _const_argnums(v)
        return nums or None
    return None


def donating_factories(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Terminal function name -> donated argnums, for every same-file
    function whose return value is a donating ``jax.jit`` call
    (``_build_fn``-style factories)."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and \
                    isinstance(sub.value, ast.Call):
                nums = donate_argnums_of(sub.value)
                if nums:
                    out[node.name] = nums
    return out


def _target_key(node) -> Optional[str]:
    """Dotted key for a Name / self-rooted Attribute chain
    (``self._residuals`` -> 'self._residuals'); None for anything the
    name-based analysis cannot track (subscripts, calls)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class DonatedUse:
    """One read of a value previously passed through a donated call
    position."""

    def __init__(self, node: ast.AST, name: str, donated_line: int,
                 callee: str):
        self.node = node
        self.name = name
        self.donated_line = donated_line
        self.callee = callee


class _DonationWalker:
    """Statement-ordered walk of one function with branch merging."""

    def __init__(self, factories: Dict[str, Tuple[int, ...]]):
        self.factories = factories
        # local name -> donated argnums of the callable it holds
        self.donating_vars: Dict[str, Tuple[int, ...]] = {}
        # tracked key -> {"line": int, "callee": str}
        self.donated: Dict[str, dict] = {}
        self.uses: List[DonatedUse] = []
        self._reported: set = set()

    # -- donating-callable resolution ----------------------------------------
    def _donation_of(self, value) -> Optional[Tuple[int, ...]]:
        """Donated argnums of the callable ``value`` evaluates to."""
        if isinstance(value, ast.Name):
            return self.donating_vars.get(value.id)
        if isinstance(value, ast.Lambda):
            return self._donation_of(value.body)
        if not isinstance(value, ast.Call):
            return None
        nums = donate_argnums_of(value)
        if nums:
            return nums
        last = call_name(value.func).split(".")[-1]
        if last in self.factories:
            return self.factories[last]
        if last == "lookup_program":
            # fn = upd.lookup_program(key, <factory>): the program the
            # cache hands back is whatever the factory builds
            for a in list(value.args[1:]) + \
                    [kw.value for kw in value.keywords]:
                nums = self._donation_of(a)
                if nums:
                    return nums
            for a in value.args[1:]:
                if isinstance(a, ast.Attribute) and \
                        a.attr in self.factories:
                    return self.factories[a.attr]
        return None

    # -- reads / kills / marks ----------------------------------------------
    def _check_reads(self, expr, skip: Tuple[ast.AST, ...] = ()) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if node in skip:
                continue
            key = None
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                key = _target_key(node)
            if key is None or key not in self.donated:
                continue
            # an attribute READ on a tracked dotted chain counts once
            if (key, node.lineno) in self._reported:
                continue
            self._reported.add((key, node.lineno))
            info = self.donated[key]
            self.uses.append(DonatedUse(node, key, info["line"],
                                        info["callee"]))

    def _kill(self, target) -> None:
        key = _target_key(target)
        if key is not None:
            self.donated.pop(key, None)
            # rebinding `fn` also drops its donating-callable tag
            self.donating_vars.pop(key, None)

    def _kill_all(self) -> None:
        self.donated.clear()

    def _is_restore_call(self, call: ast.Call) -> bool:
        last = call_name(call.func).split(".")[-1]
        if not last and isinstance(call.func, ast.Attribute):
            last = call.func.attr
        return last in _RESTORE_NAMES or \
            any(t in last for t in _RESTORE_TOKENS)

    def _process_calls(self, expr) -> None:
        """Donation marks + restore kills for every call in ``expr``
        (applied AFTER the read check: the donating call itself reads
        its arguments legally — the donation happens at that read)."""
        if expr is None:
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if self._is_restore_call(node):
                self._kill_all()
                continue
            nums = None
            if isinstance(node.func, (ast.Name, ast.Attribute)):
                key = _target_key(node.func)
                if key is not None and key in self.donating_vars:
                    nums = self.donating_vars[key]
            if nums is None:
                continue
            callee = _target_key(node.func) or "<fn>"
            for pos in nums:
                if pos >= len(node.args):
                    continue
                akey = _target_key(node.args[pos])
                if akey is not None:
                    self.donated[akey] = {"line": node.lineno,
                                          "callee": callee}

    # -- statement dispatch ---------------------------------------------------
    def visit_block(self, stmts) -> None:
        for s in stmts:
            self.visit(s)

    def _branch(self, blocks) -> None:
        """Run each block from a copy of the current state; merge:
        donated-in-any stays donated, killed-only-when-killed-in-all."""
        pre_don = dict(self.donated)
        pre_vars = dict(self.donating_vars)
        donated_arms = []
        vars_arms = []
        for block in blocks:
            self.donated = dict(pre_don)
            self.donating_vars = dict(pre_vars)
            self.visit_block(block)
            donated_arms.append(self.donated)
            vars_arms.append(self.donating_vars)
        # union of the arms: each arm started from the pre-state, so a
        # key killed in EVERY arm is absent from all of them (dead), a
        # key donated or surviving in ANY arm stays tracked
        merged: Dict[str, dict] = {}
        for arm in donated_arms:
            merged.update(arm)
        self.donated = merged
        mvars: Dict[str, Tuple[int, ...]] = {}
        for arm in vars_arms:
            mvars.update(arm)
        self.donating_vars = mvars

    def visit(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs analyzed on their own
        if isinstance(stmt, ast.Assign):
            skip: Tuple[ast.AST, ...] = ()
            root = _scatter_restore_root(stmt.value)
            if root is not None and len(stmt.targets) == 1:
                tkey = _target_key(stmt.targets[0])
                if tkey is not None and tkey == _target_key(root):
                    # scatter-update restore: the rebind kills the
                    # donated read in the same statement
                    skip = (root,)
            self._check_reads(stmt.value, skip=skip)
            self._process_calls(stmt.value)
            nums = self._donation_of(stmt.value)
            for t in stmt.targets:
                if isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        self._kill(e)
                else:
                    self._kill(t)
                    if nums is not None:
                        key = _target_key(t)
                        if key is not None:
                            self.donating_vars[key] = nums
            return
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt, ast.AugAssign):
                self._check_reads(stmt.target)
            self._check_reads(stmt.value)
            self._process_calls(stmt.value)
            self._kill(stmt.target)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._kill(t)
            return
        if isinstance(stmt, ast.Expr):
            self._check_reads(stmt.value)
            self._process_calls(stmt.value)
            return
        if isinstance(stmt, ast.Return):
            self._check_reads(stmt.value)
            self._process_calls(stmt.value)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            for part in (getattr(stmt, "exc", None),
                         getattr(stmt, "cause", None),
                         getattr(stmt, "test", None),
                         getattr(stmt, "msg", None)):
                self._check_reads(part)
                self._process_calls(part)
            return
        if isinstance(stmt, ast.If):
            self._check_reads(stmt.test)
            self._process_calls(stmt.test)
            self._branch([stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_reads(stmt.iter)
            self._process_calls(stmt.iter)
            self._kill(stmt.target)
            # two passes: catches a name donated at the bottom of one
            # iteration and read at the top of the next
            for _ in range(2):
                self._branch([stmt.body, []])
                self._kill(stmt.target)
            self.visit_block(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._check_reads(stmt.test)
            for _ in range(2):
                self._branch([stmt.body, []])
                self._check_reads(stmt.test)
            self.visit_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_reads(item.context_expr)
                self._process_calls(item.context_expr)
                if item.optional_vars is not None:
                    self._kill(item.optional_vars)
            self.visit_block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            # handlers may run from any point in the body: they see the
            # post-body state (where the donation hazard lives — the
            # failed-dispatch retry class) WITHOUT its kills erased;
            # conservative and matches the wired failure paths, which
            # donate before they raise
            self.visit_block(stmt.body)
            self._branch([h.body for h in stmt.handlers] +
                         [stmt.orelse or []])
            self.visit_block(stmt.finalbody)
            return
        # fallthrough (Pass, Global, Import, ...): check embedded exprs
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_reads(child)
                self._process_calls(child)


def analyze_donation(fn, factories: Dict[str, Tuple[int, ...]]) \
        -> List[DonatedUse]:
    """Run the use-after-donate dataflow over one function body."""
    w = _DonationWalker(factories)
    w.visit_block(fn.body)
    return w.uses
