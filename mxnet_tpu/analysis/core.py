"""graft-lint core: Finding, suppression, baseline, and the run() driver.

Repo-specific static analysis (ISSUE 7).  PRs 1-6 grew a heavily
threaded runtime and the reviews kept catching the same defect classes
by hand — reentrant-lock deadlocks, hidden device→host syncs on hot
paths, non-atomic writes, undocumented env vars, unbounded metric
labels.  This package turns those review invariants into checkers that
run in tier-1 (`make lint-graft`, tests/test_analysis.py), the same
move the big-system papers make: check system invariants mechanically,
not by reviewer vigilance (arxiv 1605.08695; MXNet's dependency engine
itself is the "ad-hoc threading doesn't scale" lesson, 1512.01274).

Design:

  * a checker is an object with ``name``, ``check_file(ctx)`` and an
    optional ``finalize()`` for cross-file rules (env-var sync);
  * per-finding suppression: ``# graft-lint: disable=<rule>[,<rule>]``
    on the finding's line or the line directly above it;
  * grandfathering: ``analysis/baseline.json`` entries match findings
    by (rule, path, symbol) and must carry a justification — the gate
    fails on NEW findings only, so the rule set can be stricter than
    the code it lands on.

Static analysis is intentionally conservative: checkers prefer missing
an exotic violation over drowning the gate in false positives (every
false positive costs either a suppression comment or reviewer trust).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# repo root = parent of the mxnet_tpu package directory; checkers that
# need repo-level context (docs/env_var.md) resolve against this, so
# the gate works regardless of the caller's cwd
PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_DIR)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    """One rule violation at a source location.

    ``symbol`` is the enclosing ``Class.method`` (or module-level name)
    — it is the stable half of the baseline key, so baselined findings
    survive unrelated line churn in the same file.
    """
    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    symbol: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def __str__(self) -> str:
        where = f"{self.path}:{self.line}:{self.col}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym}: {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "symbol": self.symbol,
                "message": self.message}


class FileCtx:
    """Parsed view of one source file handed to every checker."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # line -> set of disabled rules ("all" disables every rule).
        # A TRAILING directive (code before the '#') covers exactly its
        # own line; a COMMENT-ONLY directive line covers the next line
        # — so neither style accidentally suppresses a neighbor.
        self.suppressions: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            target = i + 1 if text[:m.start()].strip() == "" else i
            self.suppressions.setdefault(target, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "all" in rules)

    def finding(self, rule: str, node: ast.AST, message: str,
                symbol: str = "") -> Finding:
        return Finding(rule=rule, path=self.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=symbol)


def enclosing_symbols(tree: ast.AST) -> Dict[int, str]:
    """line -> dotted enclosing symbol (``Class.method``), computed once
    per file so checkers can stamp findings cheaply."""
    out: Dict[int, str] = {}

    def visit(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                name = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno)
                for ln in range(child.lineno, end + 1):
                    out[ln] = name
                visit(child, name)
            else:
                visit(child, prefix)

    visit(tree, "")
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
class Baseline:
    """Grandfathered findings: (rule, path, symbol) triples with a
    mandatory justification.  ``matches`` consumes nothing — one entry
    suppresses every finding with the same key (a function with two
    grandfathered writes is one review decision, not two)."""

    def __init__(self, entries: Sequence[dict]):
        self.entries = list(entries)
        self._keys = set()
        for e in self.entries:
            if not e.get("justification"):
                raise ValueError(
                    f"baseline entry {e} lacks a justification — "
                    "grandfathering is a review decision, write it down")
            self._keys.add((e["rule"], e["path"], e.get("symbol", "")))

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls([])
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def matches(self, finding: Finding) -> bool:
        return finding.key() in self._keys

    def rules_present(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.entries:
            out[e["rule"]] = out.get(e["rule"], 0) + 1
        return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def _relpath(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        ap = ap[len(REPO_ROOT) + 1:]
    return ap.replace(os.sep, "/")


def resolve_checkers(checkers=None) -> List:
    """Names/instances -> checker instances ('all'/None = every rule)."""
    from . import checkers as _mod
    table = _mod.registry()
    if checkers is None or checkers == "all":
        return [cls() for cls in table.values()]
    out = []
    for c in checkers:
        if isinstance(c, str):
            if c not in table:
                raise KeyError(
                    f"unknown checker '{c}'; known: {sorted(table)}")
            out.append(table[c]())
        else:
            out.append(c)
    return out


def run(checkers=None, paths: Sequence[str] = ("mxnet_tpu",),
        baseline: Optional[str] = DEFAULT_BASELINE) -> List[Finding]:
    """Run ``checkers`` over ``paths`` -> active findings.

    Inline-suppressed and baselined findings are filtered out; the
    result is what the gate fails on.  ``baseline=None`` reports
    everything (used by the baseline-refresh workflow and the unit
    fixtures).
    """
    active, _, _ = run_detailed(checkers, paths, baseline)
    return active


def run_detailed(checkers=None, paths: Sequence[str] = ("mxnet_tpu",),
                 baseline: Optional[str] = DEFAULT_BASELINE):
    """-> (active, baselined, suppressed_count)."""
    insts = resolve_checkers(checkers)
    bl = Baseline.load(baseline)
    raw: List[Finding] = []
    suppressed = 0
    resolved = []
    for p in paths:
        if not os.path.isabs(p) and not os.path.exists(p):
            p = os.path.join(REPO_ROOT, p)  # cwd-independent gate
        resolved.append(p)
    files = _iter_py_files(resolved)
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            raw.append(Finding(rule="parse-error", path=_relpath(path),
                               line=getattr(e, "lineno", 0) or 0, col=0,
                               message=f"could not parse: {e}"))
            continue
        ctx = FileCtx(path, _relpath(path), source, tree)
        symbols = enclosing_symbols(tree)
        for checker in insts:
            for f in checker.check_file(ctx):
                if not f.symbol:
                    f.symbol = symbols.get(f.line, "")
                if ctx.suppressed(f.rule, f.line):
                    suppressed += 1
                else:
                    raw.append(f)
    for checker in insts:
        fin = getattr(checker, "finalize", None)
        if fin is not None:
            raw.extend(fin())
    active = [f for f in raw if not bl.matches(f)]
    baselined = [f for f in raw if bl.matches(f)]
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    return active, baselined, suppressed
