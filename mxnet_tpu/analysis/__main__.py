"""graft-lint CLI: ``python -m mxnet_tpu.analysis [paths...]``.

Exit status: 0 = clean (baseline included), 1 = active findings or
failed program-audit contracts, 2 = usage error.  ``make lint-graft``
is the canonical invocation (sweep + ``--audit-programs``).

``--audit-programs`` (ISSUE 15) additionally runs the compiled-program
contract auditor: a tiny whole-step training program is built with HLO
capture on, and its declared contracts — donation really became
input-output aliasing, zero host callbacks, collective count matches
the plan — are verified against the lowered artifact
(``analysis/program_audit.py``).  ``--audit-only`` skips the sweep.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from .checkers import ALL_RULES
from .core import DEFAULT_BASELINE, run_detailed


def _run_audit(as_json: bool, payload=None) -> int:
    """Run the probe + audit.  Text mode prints; ``--json`` mode stashes
    the report into ``payload`` instead, so the CLI emits ONE top-level
    JSON document no matter which legs ran."""
    from . import program_audit
    t0 = time.perf_counter()
    try:
        report = program_audit.self_audit()
    except Exception as e:  # noqa: BLE001 — a broken probe must gate
        print(f"program-audit: probe workload failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    dt = time.perf_counter() - t0
    if as_json:
        doc = dict(report, seconds=round(dt, 3))
        if payload is None:
            print(json.dumps({"program_audit": doc}, indent=1))
        else:
            payload["program_audit"] = doc
    else:
        for issue in report["issues"]:
            print(f"program-audit: {issue['program']}: "
                  f"{issue['check']}: {issue['detail']}")
        print(f"program-audit: {report['checked']} program(s) checked, "
              f"{len(report['issues'])} issue(s), "
              f"skipped={report['skipped']} ({dt:.1f}s)",
              file=sys.stderr)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="graft-lint: repo-specific static analysis + "
                    "compiled-program contract audit "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                    help="files/dirs to scan (default: mxnet_tpu)")
    ap.add_argument("--rules", default=None,
                    help="comma list of rules (default: all of "
                         f"{', '.join(ALL_RULES)})")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline json (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--audit-programs", action="store_true",
                    help="after the sweep, build a small whole-step "
                         "program (HLO capture on) and verify its "
                         "compiled-program contracts: donation "
                         "aliasing, host callbacks, collective count")
    ap.add_argument("--audit-only", action="store_true",
                    help="run only the program audit, no static sweep")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0
    if args.audit_only:
        return _run_audit(args.as_json)
    rules = None if args.rules is None else \
        [r.strip() for r in args.rules.split(",") if r.strip()]
    baseline = None if args.no_baseline else args.baseline
    t0 = time.perf_counter()
    try:
        active, baselined, suppressed = run_detailed(
            rules, args.paths or ["mxnet_tpu"], baseline)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0
    payload = {
        "active": [f.to_dict() for f in active],
        "baselined": len(baselined), "suppressed": suppressed,
        "seconds": round(dt, 3)}
    if not args.as_json:
        for f in active:
            print(f)
        print(f"graft-lint: {len(active)} finding(s), "
              f"{len(baselined)} baselined, {suppressed} suppressed "
              f"({dt:.1f}s)", file=sys.stderr)
    rc = 1 if active else 0
    if args.audit_programs:
        audit_rc = _run_audit(args.as_json, payload=payload)
        rc = rc or audit_rc
    if args.as_json:
        print(json.dumps(payload, indent=1))
    return rc


if __name__ == "__main__":
    sys.exit(main())
