"""graft-lint CLI: ``python -m mxnet_tpu.analysis [paths...]``.

Exit status: 0 = clean (baseline included), 1 = active findings,
2 = usage error.  ``make lint-graft`` is the canonical invocation.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from .checkers import ALL_RULES
from .core import DEFAULT_BASELINE, run_detailed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="graft-lint: repo-specific static analysis "
                    "(docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", default=["mxnet_tpu"],
                    help="files/dirs to scan (default: mxnet_tpu)")
    ap.add_argument("--rules", default=None,
                    help="comma list of rules (default: all of "
                         f"{', '.join(ALL_RULES)})")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline json (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(r)
        return 0
    rules = None if args.rules is None else \
        [r.strip() for r in args.rules.split(",") if r.strip()]
    baseline = None if args.no_baseline else args.baseline
    t0 = time.perf_counter()
    try:
        active, baselined, suppressed = run_detailed(
            rules, args.paths or ["mxnet_tpu"], baseline)
    except KeyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0
    if args.as_json:
        print(json.dumps({
            "active": [f.to_dict() for f in active],
            "baselined": len(baselined), "suppressed": suppressed,
            "seconds": round(dt, 3)}, indent=1))
    else:
        for f in active:
            print(f)
        print(f"graft-lint: {len(active)} finding(s), "
              f"{len(baselined)} baselined, {suppressed} suppressed "
              f"({dt:.1f}s)", file=sys.stderr)
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
