"""The ten repo-specific graft-lint checkers (ISSUEs 7 + 15).

Each rule encodes a defect class a human reviewer actually caught —
the PR 7 set (thread-safety, host-sync, atomic-write, env-sync,
metrics-hygiene, memory-hygiene) works at the source level; the ISSUE
15 tier (use-after-donate, retrace-hazard, gate-hygiene, bench-emit)
guards the jit/program boundary where the bug class moved after PR 10
made the training step one opaque donated program.  The checker
docstrings name the incidents.  All checkers are AST-based and
conservative — a miss is recoverable (the sanitizer, the program
auditor, or a review catches it), a false-positive storm kills the
gate.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileCtx, Finding, PKG_DIR, REPO_ROOT

_ENV_RE = re.compile(r"^(MXNET_|MXT_)[A-Z0-9_]+$")
_ENV_DOC_RE = re.compile(r"\b((?:MXNET|MXT)_[A-Z0-9_]*\*?)")


# one dotted-call-name resolver for the whole package: dataflow.py owns
# it (the def-use pass needs it without importing this heavier module)
from .dataflow import call_name as _call_name  # noqa: E402


def _const_str(node) -> Optional[str]:
    return node.value if isinstance(node, ast.Constant) \
        and isinstance(node.value, str) else None


# ---------------------------------------------------------------------------
# 1. thread-safety
# ---------------------------------------------------------------------------
class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.locks: Dict[str, bool] = {}      # attr -> reentrant?
        self.worker_entries: Set[str] = set()
        # attr -> [(side, method, node, frozenset(held))]
        self.writes: Dict[str, list] = {}
        self.init_only: Set[str] = set()


_LOCK_CTORS = {
    "threading.Lock": False, "threading.RLock": True,
    "Lock": False, "RLock": True,
    # the sanitizer factories (mxnet_tpu.analysis.sanitizer)
    "make_lock": False, "make_rlock": True,
    "_san.make_lock": False, "_san.make_rlock": True,
    "sanitizer.make_lock": False, "sanitizer.make_rlock": True,
}
_COND_CTORS = {"threading.Condition", "Condition", "make_condition",
               "_san.make_condition", "sanitizer.make_condition"}


def _lock_ctor_reentrant(call: ast.Call) -> Optional[bool]:
    """None = not a lock construction; else the reentrancy of the lock
    bound by this call (Condition counts as its inner lock)."""
    name = _call_name(call.func)
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    if name in _COND_CTORS or name.endswith(".Condition"):
        # an explicit reentrant= kwarg or inner lock wins; a BARE
        # Condition() defaults to an RLock (threading.Condition's
        # documented default), so it IS reentrant
        for kw in call.keywords:
            if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        for a in call.args:
            if isinstance(a, ast.Call):
                inner = _lock_ctor_reentrant(a)
                if inner is not None:
                    return inner
        return True


class ThreadSafetyChecker:
    """Classes that spawn ``threading.Thread`` must guard shared mutable
    attributes with a held lock (the PR 6 hung-future reviews), and a
    non-reentrant lock must not be re-acquirable on the same thread
    (the PR 5 SIGTERM-mid-save deadlock class).

    Flags (a) ``self.attr = ...`` rebinds reachable from BOTH the worker
    thread and non-worker methods with no common must-held lock, and
    (b) acquisition of ``self.X`` while a path already holds ``self.X``
    and X is non-reentrant.  ``__init__`` writes are construction
    (happens-before ``Thread.start``), never flagged.
    """

    name = "thread-safety"
    _MAX_DEPTH = 12

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        return out

    # -- per-class analysis --------------------------------------------------
    def _check_class(self, ctx: FileCtx, cls: ast.ClassDef) -> List[Finding]:
        info = _ClassInfo(cls)
        for item in cls.body:
            if isinstance(item, ast.FunctionDef):
                info.methods[item.name] = item
        # pass 1: lock attrs + worker entries (Thread(target=...))
        local_workers: List[ast.FunctionDef] = []
        for mname, m in info.methods.items():
            local_defs = {n.name: n for n in ast.walk(m)
                          if isinstance(n, ast.FunctionDef) and n is not m}
            for n in ast.walk(m):
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                    re_ent = _lock_ctor_reentrant(n.value)
                    if re_ent is not None:
                        for t in n.targets:
                            if isinstance(t, ast.Attribute) and \
                                    isinstance(t.value, ast.Name) and \
                                    t.value.id == "self":
                                info.locks[t.attr] = re_ent
                if isinstance(n, ast.Call) and \
                        _call_name(n.func).endswith("Thread"):
                    for kw in n.keywords:
                        if kw.arg != "target":
                            continue
                        v = kw.value
                        if isinstance(v, ast.Attribute) and \
                                isinstance(v.value, ast.Name) and \
                                v.value.id == "self":
                            info.worker_entries.add(v.attr)
                        elif isinstance(v, ast.Name) and v.id in local_defs:
                            # closure worker (predictor._poll): analyze
                            # the local def as worker-side code
                            local_workers.append(local_defs[v.id])
        if not info.worker_entries and not local_workers:
            return []
        qual = cls.name
        reentry: List[Finding] = []
        sink: list = []   # (attr, side, method, node, held)
        seen: Set[tuple] = set()

        # pass 2: walk methods with must-held lock tracking
        def walk(fn: ast.FunctionDef, held: frozenset, side: str,
                 chain: Tuple[str, ...]):
            if len(chain) >= self._MAX_DEPTH or \
                    (fn.name, held, side) in seen:
                return
            seen.add((fn.name, held, side))
            for stmt in fn.body:
                visit(fn, stmt, held, side, chain + (fn.name,))

        def visit(fn, stmt, held, side, chain):
            if isinstance(stmt, ast.With):
                new_held = set(held)
                for item in stmt.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self" and e.attr in info.locks:
                        if e.attr in held and not info.locks[e.attr]:
                            reentry.append(ctx.finding(
                                self.name, e,
                                f"non-reentrant lock 'self.{e.attr}' is "
                                f"re-acquired on a thread that already "
                                f"holds it (path: {' -> '.join(chain)}) "
                                f"— guaranteed deadlock; use an RLock "
                                f"or restructure",
                                symbol=f"{qual}.{fn.name}"))
                        new_held.add(e.attr)
                for s in stmt.body:
                    visit(fn, s, frozenset(new_held), side, chain)
                return
            if isinstance(stmt, (ast.If, ast.For, ast.While)):
                for s in list(stmt.body) + list(stmt.orelse):
                    visit(fn, s, held, side, chain)
                return
            if isinstance(stmt, ast.Try):
                for s in (list(stmt.body) + list(stmt.orelse)
                          + list(stmt.finalbody)
                          + [h for hh in stmt.handlers for h in hh.body]):
                    visit(fn, s, held, side, chain)
                return
            # attribute rebinds + self-method calls in plain statements
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        record_write(fn, t, node, held, side)
                elif isinstance(node, ast.AugAssign):
                    record_write(fn, node.target, node, held, side)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    callee = info.methods.get(node.func.attr)
                    if callee is not None and callee.name != fn.name:
                        walk(callee, held, side, chain)

        def record_write(fn, target, node, held, side):
            if fn.name == "__init__":
                return
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                sink.append((target.attr, side, fn.name, node, held))

        worker_names = set(info.worker_entries)
        for m in sorted(worker_names):
            if m in info.methods:
                walk(info.methods[m], frozenset(), "worker", ())
        for lw in local_workers:
            walk(lw, frozenset(), "worker", ())
        worker_reached = {s[2] for s in sink if s[1] == "worker"}
        seen.clear()
        for mname, m in info.methods.items():
            if mname == "__init__" or mname in worker_names:
                continue
            walk(m, frozenset(), "caller", ())

        # pass 3: write/write conflicts without a common must-held lock
        findings: List[Finding] = list(reentry)
        by_attr: Dict[str, list] = {}
        for attr, side, method, node, held in sink:
            by_attr.setdefault(attr, []).append((side, method, node, held))
        for attr, rows in sorted(by_attr.items()):
            if attr in info.locks:
                continue
            w = [r for r in rows if r[0] == "worker"]
            c = [r for r in rows if r[0] == "caller"
                 and r[1] not in worker_reached]
            if not w or not c:
                continue
            common = None
            for _, _, _, held in w + c:
                common = set(held) if common is None else common & set(held)
            if common:
                continue
            _, method, node, held = (c + w)[0]
            others = sorted({f"{qual}.{m}" for _, m, _, _ in w})
            findings.append(ctx.finding(
                self.name, node,
                f"attribute 'self.{attr}' is written both from the "
                f"worker thread ({', '.join(others)}) and from "
                f"{qual}.{method} with no common lock held — guard "
                f"both writes with one of "
                f"{sorted(info.locks) or ['a lock']}",
                symbol=f"{qual}.{method}"))
        return findings


# ---------------------------------------------------------------------------
# 2. host-sync
# ---------------------------------------------------------------------------
_SYNC_ATTRS = {"asnumpy", "asscalar", "item", "block_until_ready",
               "wait_to_read", "wait_to_write"}
_SYNC_CALLS = {"np.asarray", "_np.asarray", "numpy.asarray",
               "np.array", "_np.array"}


class HostSyncChecker:
    """No device→host synchronization inside ``@analysis.hot_path``
    functions or functions handed to ``jax.jit`` (the round-2/round-4
    dispatch-count regressions, caught statically).

    A ``.asnumpy()`` / ``float(nd)`` / ``np.asarray`` /
    ``block_until_ready`` on a hot path stalls the PJRT pipeline and
    turns O(1)-dispatch steps back into blocking ones.  The check is
    transitive over same-file calls (``self.m()`` and module-level
    functions) from every hot entry.
    """

    name = "host-sync"
    _MAX_DEPTH = 16

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        funcs: Dict[str, ast.FunctionDef] = {}   # qualified name -> def
        methods: Dict[str, Dict[str, ast.FunctionDef]] = {}
        hot: List[Tuple[str, ast.FunctionDef, Optional[str]]] = []

        def collect(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    collect(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = f"{cls}.{child.name}" if cls else child.name
                    funcs[qual] = child
                    if cls:
                        methods.setdefault(cls, {})[child.name] = child
                    else:
                        methods.setdefault("", {})[child.name] = child
                    for dec in child.decorator_list:
                        dn = _call_name(dec) if not isinstance(dec, ast.Call) \
                            else _call_name(dec.func)
                        if dn.split(".")[-1] == "hot_path" or \
                                dn in ("jax.jit", "_jax.jit"):
                            hot.append((qual, child, cls))
                    collect(child, cls)

        collect(ctx.tree, None)
        # functions passed to jax.jit(...) positionally are hot entries
        jit_args: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) in ("jax.jit", "_jax.jit"):
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        jit_args.add(a.id)
                    elif isinstance(a, ast.Attribute) and \
                            isinstance(a.value, ast.Name) and \
                            a.value.id == "self":
                        jit_args.add(a.attr)
        hot_quals = {q for q, _, _ in hot}
        for qual, fn in funcs.items():
            if fn.name in jit_args and qual not in hot_quals:
                cls = qual.rsplit(".", 1)[0] if "." in qual else None
                hot.append((qual, fn, cls))

        out: List[Finding] = []
        for qual, fn, cls in hot:
            seen: Set[str] = set()
            self._scan(ctx, fn, cls, (qual,), methods, seen, out)
        return out

    @staticmethod
    def _host_math(node) -> bool:
        """int/float of host-static expressions is not a device sync:
        numpy/math shape arithmetic (int(np.prod(shape))), env/config
        parsing (float(getenv(...))), and ``x.shape[i]`` accesses."""
        if isinstance(node, ast.Call):
            cn = _call_name(node.func)
            root = cn.split(".")[0]
            leaf = cn.split(".")[-1]
            return root in ("np", "_np", "numpy", "math", "len",
                            "builtins") or \
                leaf in ("getenv", "get", "len", "float", "int")
        if isinstance(node, ast.Subscript):
            v = node.value
            return isinstance(v, ast.Attribute) and \
                v.attr in ("shape", "sizes", "strides", "buckets")
        return False

    def _scan(self, ctx, fn, cls, chain, methods, seen, out):
        key = chain[-1]
        if key in seen or len(chain) > self._MAX_DEPTH:
            return
        seen.add(key)
        entry = chain[0]
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cn = _call_name(node.func)
            sync = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _SYNC_ATTRS and not node.args:
                sync = f".{node.func.attr}()"
            elif cn in _SYNC_CALLS:
                sync = f"{cn}(...)"
            elif cn in ("float", "int") and node.args and isinstance(
                    node.args[0], (ast.Call, ast.Subscript)) and \
                    not self._host_math(node.args[0]):
                # float(x.sum()) — a device value materialized to host.
                # Bare names are skipped (float(scale) on a python
                # scalar is everywhere), as is numpy/math shape
                # arithmetic (int(np.prod(shape)) is host-static).
                sync = f"{cn}(<expr>)"
            if sync is not None:
                via = "" if len(chain) == 1 else \
                    f" (via {' -> '.join(chain)})"
                out.append(ctx.finding(
                    self.name, node,
                    f"device->host sync {sync} reachable from hot path "
                    f"'{entry}'{via} — hot paths must stay async "
                    f"(move the read off-path, use metrics gauges, or "
                    f"suppress with justification)"))
                continue
            # transitive: self.m() within the class, bare f() in module
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "self" and cls:
                callee = methods.get(cls, {}).get(node.func.attr)
                if callee is not None:
                    self._scan(ctx, callee, cls,
                               chain + (f"{cls}.{callee.name}",),
                               methods, seen, out)
            elif isinstance(node.func, ast.Name):
                callee = methods.get("", {}).get(node.func.id)
                if callee is not None:
                    self._scan(ctx, callee, None,
                               chain + (callee.name,), methods, seen,
                               out)


# ---------------------------------------------------------------------------
# 3. atomic-write
# ---------------------------------------------------------------------------
_EXEMPT_FILES = ("mxnet_tpu/base.py", "mxnet_tpu/checkpoint/layout.py")
_WRITE_CALLS = {"np.savez", "_np.savez", "np.savez_compressed",
                "_np.savez_compressed", "np.save", "_np.save",
                "json.dump", "_json.dump"}


class AtomicWriteChecker:
    """Persistent files must be written crash-atomically: via
    ``base.atomic_write``, ``checkpoint/layout.py``, or the
    tmp-then-``os.replace`` idiom in the same function (the PR 5 review
    found five writers that could leave torn files; this pins the fix).

    Flags ``open(path, 'w'/'wb'/'a')``, ``np.savez``, ``json.dump`` in
    any other context.  A function that also calls ``os.replace`` (or
    ``atomic_write``) is using the idiom and passes.
    """

    name = "atomic-write"

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        if ctx.relpath.endswith(_EXEMPT_FILES):
            return []
        # map each function to whether it uses the atomic idiom
        out: List[Finding] = []
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        covered: List[Tuple[int, int, bool]] = []
        for fn in funcs:
            atomic = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    cn = _call_name(node.func)
                    if cn in ("os.replace", "os.rename") or \
                            cn.split(".")[-1] == "atomic_write":
                        atomic = True
                        break
            covered.append((fn.lineno,
                            getattr(fn, "end_lineno", fn.lineno), atomic))

        def in_atomic_fn(line: int) -> bool:
            # innermost enclosing function wins
            best = None
            for lo, hi, atomic in covered:
                if lo <= line <= hi and \
                        (best is None or lo > best[0]):
                    best = (lo, atomic)
            return best[1] if best else False

        # names bound to in-memory buffers: np.save(buf)/json.dump(.., buf)
        # into a BytesIO/StringIO is not a persistent write
        membuf: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                vn = _call_name(node.value.func)
                if vn.split(".")[-1] in ("BytesIO", "StringIO"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            membuf.add(t.id)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = _call_name(node.func)
            mode = None
            if cn == "open" or cn.endswith(".open") and cn != "os.open":
                mode = "r"
                if len(node.args) >= 2:
                    mode = _const_str(node.args[1]) or ""
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = _const_str(kw.value) or ""
                base = mode.replace("b", "").replace("t", "") \
                           .replace("+", "")
                if base not in ("w", "a", "x"):
                    continue
            elif cn not in _WRITE_CALLS:
                continue
            else:
                # np.save(buf, ...) / json.dump(obj, buf): in-memory
                # targets are exempt (position of the file arg differs
                # by callee; any BytesIO/StringIO name among the args
                # qualifies)
                if any(isinstance(a, ast.Name) and a.id in membuf
                       for a in node.args):
                    continue
            if in_atomic_fn(node.lineno):
                continue
            what = f"open(..., '{mode}')" if mode else f"{cn}(...)"
            out.append(ctx.finding(
                self.name, node,
                f"{what} writes a persistent file non-atomically — a "
                f"crash mid-write leaves a torn file.  Use "
                f"base.atomic_write / checkpoint.layout, or write to a "
                f"same-dir tmp and os.replace"))
        return out


# ---------------------------------------------------------------------------
# 4. env-sync
# ---------------------------------------------------------------------------
# roots searched for the docs→code direction: variables honored outside
# the python package (native runtime, harness scripts) or read through
# helpers the AST pass can't follow still count as read.  The package
# itself is included so a PARTIAL scan (one file) never turns every
# documented variable into a "stale row".  Paths are repo-relative.
_ENV_EXTRA_ROOTS = ("mxnet_tpu", "src", "tools", "bench.py", "benchmark",
                    "watchdog_util.py", "__graft_entry__.py",
                    "experiments", "tests", "tests_tpu", "example")
_ENV_DOC = os.path.join("docs", "env_var.md")


class EnvVarSyncChecker:
    """Every ``MXNET_*`` / ``MXT_*`` variable the package reads must be
    documented in docs/env_var.md, and every documented variable must
    be read somewhere (package, native runtime, or harness) — the PR
    1-6 reviews each found knobs that shipped undocumented.

    Reads are detected as ``os.environ.get/[]/setdefault``,
    ``os.getenv`` and ``base.getenv`` calls with a literal name.  Doc
    tokens ending in ``*`` are prefix wildcards (``MXT_BENCH_*``).
    """

    name = "env-sync"

    def __init__(self, doc_path: Optional[str] = None,
                 extra_roots: Sequence[str] = _ENV_EXTRA_ROOTS):
        self.doc_path = doc_path or os.path.join(REPO_ROOT, _ENV_DOC)
        self.extra_roots = extra_roots
        self._reads: List[Tuple[str, FileCtx, ast.AST]] = []
        self._indirect: Set[str] = set()

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        for node in ast.walk(ctx.tree):
            name = self._read_name(node)
            if name and _ENV_RE.match(name):
                self._reads.append((name, ctx, node))
            elif isinstance(node, ast.Call):
                # indirection reads: a literal env name handed to a
                # helper (parse_bucket_env("MXNET_SERVE_BUCKETS")).
                # Counts for the docs→code direction only — the
                # code→docs direction stays strict on direct reads.
                for a in node.args:
                    s = _const_str(a)
                    if s and _ENV_RE.match(s):
                        self._indirect.add(s)
        return []

    @staticmethod
    def _read_name(node) -> Optional[str]:
        if isinstance(node, ast.Call):
            cn = _call_name(node.func)
            if cn in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv", "_base.getenv", "base.getenv",
                      "os.environ.setdefault", "environ.setdefault") \
                    and node.args:
                return _const_str(node.args[0])
        if isinstance(node, ast.Subscript):
            base = _call_name(node.value)
            if base in ("os.environ", "environ"):
                sl = node.slice
                if isinstance(sl, ast.Index):  # py<3.9 compat shape
                    sl = sl.value
                return _const_str(sl)
        return None

    def _doc_tokens(self) -> Tuple[Set[str], List[str]]:
        try:
            with open(self.doc_path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            return set(), []
        tokens = set(_ENV_DOC_RE.findall(text))
        exact = {t for t in tokens if not t.endswith("*")}
        # wildcard rows (`MXT_BENCH_*`) document a family — but a bare
        # brand prefix (the prose says "the MXNET_* knobs") documents
        # nothing and must not become a catch-all
        prefixes = [t[:-1] for t in tokens
                    if t.endswith("*") and t[:-1] not in ("MXNET_", "MXT_")]
        return exact, prefixes

    def finalize(self) -> List[Finding]:
        exact, prefixes = self._doc_tokens()
        out: List[Finding] = []
        read_names: Set[str] = set()
        doc_rel = os.path.relpath(self.doc_path, REPO_ROOT) \
            .replace(os.sep, "/")
        reported: Set[str] = set()
        for name, ctx, node in self._reads:
            read_names.add(name)
            if name in exact or any(name.startswith(p) for p in prefixes):
                continue
            if name in reported:
                continue   # one finding per variable, at its first read
            reported.add(name)
            out.append(ctx.finding(
                self.name, node,
                f"env var '{name}' is read here but not documented in "
                f"{doc_rel} — add a row (name, default, meaning)"))
        # docs -> code: documented vars nobody reads anywhere
        undocumented_side = exact - read_names - self._indirect
        if undocumented_side:
            extra_text = self._extra_corpus()
            for name in sorted(undocumented_side):
                if name in extra_text:
                    continue
                out.append(Finding(
                    rule=self.name, path=doc_rel, line=1, col=0,
                    symbol=name,
                    message=f"env var '{name}' is documented in "
                            f"{doc_rel} but never read by the package, "
                            f"native runtime, or harness — stale row?"))
        return out

    def _extra_corpus(self) -> str:
        chunks: List[str] = []
        for root in self.extra_roots:
            p = os.path.join(REPO_ROOT, root)
            if os.path.isfile(p):
                try:
                    with open(p, encoding="utf-8",
                              errors="ignore") as f:
                        chunks.append(f.read())
                except OSError:
                    pass
                continue
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fname in filenames:
                    if not fname.endswith((".py", ".cc", ".h", ".sh")):
                        continue
                    try:
                        with open(os.path.join(dirpath, fname),
                                  encoding="utf-8",
                                  errors="ignore") as f:
                            chunks.append(f.read())
                    except OSError:
                        pass
        return "\n".join(chunks)


# ---------------------------------------------------------------------------
# 5. metrics-hygiene
# ---------------------------------------------------------------------------
class MetricsHygieneChecker:
    """Metric names, label VALUES, and flight-recorder phase names must
    come from bounded sets — an f-string / %-format / .format() value
    is unbounded cardinality (the PR 6 per-tenant series leak: every
    distinct string becomes a forever-живая time series in the registry
    and the scrape; ISSUE 8 extends the same rule to ``phase_span``
    names, each of which is a forever-entry in ``flight.summary()`` and
    an EWMA slot in the slow-phase watchdog).

    Flags dynamic strings passed as label kwargs to ``.inc/.set/.dec``
    on ALL-CAPS metric objects, non-literal metric names in
    ``Counter/Gauge/Histogram`` constructions, and dynamically built
    phase names passed to ``phase_span(...)``.  ``type(e).__name__``
    and plain variables are allowed — bounded sets routed through a
    variable are the normal idiom; string BUILDING at the call site is
    the defect.
    """

    name = "metrics-hygiene"

    @staticmethod
    def _is_metric_recv(node: ast.Attribute) -> bool:
        v = node.value
        last = v.attr if isinstance(v, ast.Attribute) else \
            v.id if isinstance(v, ast.Name) else ""
        return bool(last) and last == last.upper() and \
            any(c.isalpha() for c in last)

    @staticmethod
    def _dynamic_str(node) -> Optional[str]:
        if isinstance(node, ast.JoinedStr):
            return "f-string"
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Mod)):
            for side in (node.left, node.right):
                if _const_str(side) is not None or \
                        isinstance(side, ast.JoinedStr):
                    return "string concatenation/%-format"
        if isinstance(node, ast.Call):
            cn = _call_name(node.func)
            if cn.endswith(".format"):
                return ".format()"
            if cn == "str" and node.args and not isinstance(
                    node.args[0], ast.Constant):
                return "str(<expr>)"
        return None

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # label values on metric mutators
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("inc", "set", "dec") and \
                    self._is_metric_recv(node.func):
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    why = self._dynamic_str(kw.value)
                    if why:
                        out.append(ctx.finding(
                            self.name, kw.value,
                            f"label '{kw.arg}' gets a dynamically built "
                            f"value ({why}) — label values must come "
                            f"from a bounded set or the metric's "
                            f"cardinality is unbounded (fold/bound the "
                            f"value first; see Counter.fold_label)"))
            # metric names at construction
            cn = _call_name(node.func)
            if cn.split(".")[-1] in ("Counter", "Gauge", "Histogram") \
                    and node.args:
                name_arg = node.args[0]
                if _const_str(name_arg) is None and \
                        self._dynamic_str(name_arg):
                    out.append(ctx.finding(
                        self.name, name_arg,
                        "metric name is dynamically built — names must "
                        "be literal so the registry and dashboards are "
                        "enumerable"))
            # flight-recorder phase names (ISSUE 8): phase_span("x"),
            # flight.record("x", ...) — every distinct name is an
            # unbounded entry in flight.summary() + a watchdog EWMA
            # slot.  `phase_span` is distinctive enough to match under
            # ANY receiver (x.phase_span / profiler.phase_span / bare);
            # `record` is too generic, so it stays allowlisted to
            # flight-ish bases (other aliases escape — conservative by
            # design, a miss is recoverable)
            last = cn.split(".")[-1]
            if (last == "phase_span"
                    or (last == "record"
                        and cn.split(".")[0] in ("record", "flight",
                                                 "_flight", "fl"))) and \
                    node.args:
                name_arg = node.args[0]
                why = self._dynamic_str(name_arg)
                if why:
                    out.append(ctx.finding(
                        self.name, name_arg,
                        f"flight-recorder phase name is dynamically "
                        f"built ({why}) — phase names must come from a "
                        f"bounded literal set (unbounded phase "
                        f"cardinality grows flight.summary() and the "
                        f"watchdog EWMA table forever; put the varying "
                        f"part in labels=... instead)"))
            # program-introspection names (ISSUE 13): note_program /
            # note_jit program names and named_scope / layer_scope
            # layer names are forever-entries in the program registry
            # and the known-scope set — the PR 6/PR 8 cardinality
            # class.  `named_scope`/`layer_scope`/`note_program`/
            # `note_jit` are distinctive enough to match under ANY
            # receiver; a varying-but-bounded qualifier belongs in
            # note_program's label= (which is checked too — pass a
            # bounded helper's result like bucket_label, never build
            # the string at the call site).
            if last in ("note_program", "note_jit", "named_scope",
                        "layer_scope") and node.args:
                name_arg = node.args[0]
                why = self._dynamic_str(name_arg)
                if why:
                    out.append(ctx.finding(
                        self.name, name_arg,
                        f"program/layer name is dynamically built "
                        f"({why}) — note_program/named_scope names must "
                        f"come from a bounded set (each distinct name "
                        f"is a forever-entry in the program registry / "
                        f"known-scope table; use note_program's label= "
                        f"with a bounded helper for the varying part)"))
                if last in ("note_program", "note_jit"):
                    for kw in node.keywords:
                        if kw.arg == "label":
                            why = self._dynamic_str(kw.value)
                            if why:
                                out.append(ctx.finding(
                                    self.name, kw.value,
                                    f"note_program label is dynamically "
                                    f"built ({why}) — labels must come "
                                    f"from a bounded set (e.g. the "
                                    f"bucket lattice via bucket_label)"))
            # run-journal / goodput-ledger names (ISSUE 16): every
            # distinct journal.emit event name is a grep key operators
            # and the offline reporter enumerate, and every
            # goodput.attribute reason is a row in the badput taxonomy
            # + a mxnet_badput_seconds_total label — the same
            # unbounded-cardinality class as phase names.  `emit` and
            # `attribute` are too generic for any-receiver matching,
            # so they stay allowlisted to journal-/goodput-ish bases
            # (the same conservative posture as `record` above).
            if ((last == "emit"
                 and cn.split(".")[0] in ("journal", "_journal", "jr"))
                or (last == "attribute"
                    and cn.split(".")[0] in ("goodput", "_goodput",
                                             "gp"))) and node.args:
                name_arg = node.args[0]
                why = self._dynamic_str(name_arg)
                if why:
                    out.append(ctx.finding(
                        self.name, name_arg,
                        f"journal event / badput reason is dynamically "
                        f"built ({why}) — event names and goodput "
                        f"classes must come from a bounded literal set "
                        f"(each distinct name is a forever grep key in "
                        f"the run journal and a "
                        f"mxnet_badput_seconds_total label; put the "
                        f"varying part in the entry's fields instead)"))
        return out


class MemoryHygieneChecker:
    """Device-array creation must stay attributable (ISSUE 9): a
    ``jax.device_put`` whose result the HBM ledger can never see is a
    buffer the OOM post-mortem reports as untagged — the exact
    dark-bytes class the ledger exists to eliminate.

    A ``device_put`` call site passes when any of:

      * its result feeds an ``NDArray(...)`` construction in the same
        expression — NDArray.__init__ ledger-registers the wrapper;
      * it sits lexically inside a ``with memory_scope("tag")`` block
        (any receiver: ``memory_scope`` / ``_mem.memory_scope``);
      * its RESULT flows into a ledger call in the same function: the
        name the device_put is assigned to is later an argument to
        ``register``/``register_nd``/``register_host``/
        ``note_compiled``/``._set_data``/``NDArray(...)`` — the
        "ledger-registered helper" idiom (predictor ``_to_dev``).
        Per-VALUE on purpose: a function that registers one buffer
        does not whitelist its other device_puts (an unrelated
        ``_set_data`` elsewhere in the function must not hide a
        retained, never-registered copy);
      * the file IS the ledger (``observability/``).

    Transient device→device redistribution (mesh placement in
    ``parallel/``, eager sp-op staging) carries justified inline
    suppressions — same policy as every other rule.
    """

    name = "memory-hygiene"

    _REGISTER_FNS = ("register", "register_nd", "register_host",
                     "note_compiled", "_set_data")

    @staticmethod
    def _last_name(func) -> str:
        """Terminal name of a call target, tolerant of subscripted
        receivers (``self.arg_dict[k]._set_data`` -> ``_set_data``,
        which ``_call_name`` gives up on)."""
        if isinstance(func, ast.Attribute):
            return func.attr
        return _call_name(func).split(".")[-1]

    @staticmethod
    def _is_device_put(node: ast.Call) -> bool:
        return MemoryHygieneChecker._last_name(node.func) == "device_put"

    @classmethod
    def _is_register_call(cls, func) -> bool:
        last = cls._last_name(func)
        if last not in cls._REGISTER_FNS:
            return False
        if last != "register":
            return True
        # a bare `.register` is everywhere (atexit, base.Registry, the
        # ops registry) — only a ledger receiver whitelists device_puts
        if isinstance(func, ast.Attribute):
            recv = _call_name(func.value).split(".")[-1]
            return recv in ("memory", "_memory", "_mem")
        return False

    @staticmethod
    def _in_memory_scope(node, parents) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call) and _call_name(
                            ce.func).split(".")[-1] == "memory_scope":
                        return True
            cur = parents.get(cur)
        return False

    @classmethod
    def _feeds_registered_call(cls, node, parents) -> bool:
        """Nested (transitively) inside an NDArray(...) construction or
        a ledger-register/_set_data call's argument list."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.Call):
                if cls._last_name(cur.func).endswith("NDArray") or \
                        cls._is_register_call(cur.func):
                    return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = parents.get(cur)
        return False

    @classmethod
    def _result_reaches_register(cls, node, parents) -> bool:
        """Per-VALUE helper idiom: the name(s) the device_put's
        enclosing assignment binds are later an argument to a ledger
        register / ``_set_data`` / ``NDArray(...)`` call in the same
        function.  A value that escapes through a lambda or is never
        name-bound is opaque to this — suppress with justification."""
        stmt, fn, p = None, None, parents.get(node)
        while p is not None:
            if isinstance(p, ast.Lambda):
                return False
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = p
                break
            if stmt is None and isinstance(
                    p, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                stmt = p
            p = parents.get(p)
        if fn is None or stmt is None:
            return False
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        names = {sub.id for t in targets for sub in ast.walk(t)
                 if isinstance(sub, ast.Name)}
        if not names:
            return False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            if not (cls._is_register_call(sub.func)
                    or cls._last_name(sub.func).endswith("NDArray")):
                continue
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                if any(isinstance(n, ast.Name) and n.id in names
                       for n in ast.walk(arg)):
                    return True
        return False

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        rel = ctx.relpath.replace("\\", "/")
        if "/observability/" in rel or rel.startswith("observability/"):
            return []
        out: List[Finding] = []
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not self._is_device_put(node):
                continue
            if self._feeds_registered_call(node, parents):
                continue
            if self._in_memory_scope(node, parents):
                continue
            if self._result_reaches_register(node, parents):
                continue
            out.append(ctx.finding(
                self.name, node,
                "device_put outside a memory_scope / ledger-registered "
                "helper — the resulting buffer is invisible to the HBM "
                "ledger (untagged in memory.report() and the OOM "
                "post-mortem).  Wrap the creation in `with "
                "memory_scope(\"<tag>\")`, register the result "
                "(memory.register), or route it through NDArray"))
        return out


# ---------------------------------------------------------------------------
# 7. use-after-donate (ISSUE 15)
# ---------------------------------------------------------------------------
class UseAfterDonateChecker:
    """No read of a value previously passed through a donated jit call
    position (the PR 10 "the failed call may have consumed donated
    buffers" class, PR 12's donation-safe retry, PR 14's
    transient-device-copy double-count — jax reports these as an opaque
    "Array has been deleted" at some LATER access, far from the
    dispatch that killed the buffer).

    Runs the ``analysis.dataflow`` def-use pass per function: donating
    callables are recognized by construction (``jax.jit(...,
    donate_argnums=...)``), through same-file factories
    (``_build_fn``-style returns) and the ``lookup_program`` cache;
    rebinds / ``del`` / the supervisor-restore idioms
    (``*restore*`` / ``_load_init`` / ``set_states_bytes`` /
    ``readmit`` / ``_set_data``) kill the taint, as does the
    scatter-update restore idiom ``x = x.at[ids].set(...)`` (ISSUE 20:
    the whole-step embedding update rebinds the donated table to the
    functional scatter result in the same statement, so the RHS read
    is the aliasing flow, not a stale use).  The MXNET_SANITIZE
    runtime twin (``sanitizer.poison_donated``) raises a typed
    ``DonatedBufferError`` for whatever escapes the static net.
    """

    name = "use-after-donate"

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        from . import dataflow as _df
        factories = _df.donating_factories(ctx.tree)
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for use in _df.analyze_donation(node, factories):
                out.append(ctx.finding(
                    self.name, use.node,
                    f"'{use.name}' was passed through a donated "
                    f"argument of {use.callee}(...) at line "
                    f"{use.donated_line} — its buffer belongs to XLA "
                    f"now and this read sees a deleted array.  Rebind "
                    f"the name from the program's outputs, or restore "
                    f"from host copies before reusing it"))
        return out


# ---------------------------------------------------------------------------
# 8. retrace-hazard (ISSUE 15)
# ---------------------------------------------------------------------------
#: files allowed to construct jit programs — the compile chokepoints
#: program introspection instruments (executor, CachedOp, FusedUpdater,
#: whole-step, serving) plus the op/kernel registries whose jits are
#: module-lifetime singletons.  Everything else building a program is a
#: retrace hazard until reviewed (suppress/baseline with justification).
_JIT_CHOKEPOINTS = (
    "mxnet_tpu/executor.py",
    "mxnet_tpu/gluon/block.py",
    "mxnet_tpu/gluon/wholestep.py",
    # the scanned K-step superstep: same chokepoint discipline as the
    # whole step (programs cached via FusedUpdater.lookup_program keyed
    # on (policy, opt, K, ...), captured via introspect.note_jit)
    "mxnet_tpu/autotune/superstep.py",
    "mxnet_tpu/gluon/parameter.py",
    "mxnet_tpu/optimizer.py",
    "mxnet_tpu/serving/predictor.py",
    # continuous-batching decode: ONE module-lifetime jit closure per
    # engine, AOT-compiled per (slots, pages) lattice key in
    # precompile() and captured via note_program("decode_step")
    "mxnet_tpu/serving/decode.py",
    "mxnet_tpu/predictor.py",
    "mxnet_tpu/module/module.py",
    "mxnet_tpu/ops/registry.py",
    "mxnet_tpu/kvstore.py",
    "mxnet_tpu/parallel/collectives.py",
    "mxnet_tpu/parallel/data_parallel.py",
    "mxnet_tpu/symbol/symbol.py",
    "mxnet_tpu/symbol/graph.py",
    "mxnet_tpu/ndarray/sparse.py",
    "mxnet_tpu/image.py",
    "mxnet_tpu/rtc.py",
    "mxnet_tpu/export.py",
)


class RetraceHazardChecker:
    """Compiled-program identity must be stable (the
    FUSED_DTYPE_RECOMPILES class: a silent retrace/fallback re-pays XLA
    compilation on a hot path, or — worse — silently reuses a program
    traced for different semantics).  Three shapes:

      * ``jax.jit(f)(x)`` — jit-then-call in one expression builds a
        fresh program cache per evaluation: every call recompiles;
      * ``jax.jit`` inside a loop body — one program per iteration;
      * ``jax.jit`` call sites outside the blessed compile chokepoints
        (``_JIT_CHOKEPOINTS``) — programs built where introspection /
        dispatch-count gates can't see them;
      * unstable/unhashable values in a dispatch-stability cache key:
        list/set/dict displays (unhashable — a TypeError at best) and
        ``id(...)`` (a recycled address aliases a NEW object onto a
        dead entry's program — the ``_PLAN_UID`` incident) in any
        ``lookup_program(key, ...)`` argument or a local ``key``
        assignment feeding one.
    """

    name = "retrace-hazard"

    @staticmethod
    def _scope_of(node, parents):
        """Nearest enclosing function (or None = module scope) — cache
        keys resolve per-scope so an unrelated local named ``key`` in
        another function can never shadow a blessed one."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = parents.get(cur)
        return None

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        blessed = any(ctx.relpath.endswith(p) for p in _JIT_CHOKEPOINTS)
        # (scope, name) -> value expr, scoped to the enclosing function
        key_exprs: Dict[tuple, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                scope = self._scope_of(node, parents)
                key_exprs[(scope, node.targets[0].id)] = node.value
            if not isinstance(node, ast.Call):
                continue
            cn = _call_name(node.func)
            if cn in ("jax.jit", "_jax.jit"):
                if not blessed:
                    out.append(ctx.finding(
                        self.name, node,
                        "jax.jit call site outside the blessed compile "
                        "chokepoints — programs built here escape "
                        "introspection capture and the dispatch-count "
                        "gates.  Route through an existing chokepoint "
                        "(executor / CachedOp / FusedUpdater / "
                        "whole-step / serving), or suppress with the "
                        "caching story written down"))
                inner = parents.get(node)
                if isinstance(inner, ast.Call) and inner.func is node:
                    out.append(ctx.finding(
                        self.name, node,
                        "jax.jit(f)(...) — jit-then-call in one "
                        "expression builds a fresh program cache per "
                        "evaluation, so EVERY call recompiles.  Bind "
                        "the jitted callable once and reuse it"))
                cur = parents.get(node)
                while cur is not None:
                    if isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)):
                        break
                    if isinstance(cur, (ast.For, ast.While)):
                        out.append(ctx.finding(
                            self.name, node,
                            "jax.jit constructed inside a loop — one "
                            "fresh program (and XLA compile) per "
                            "iteration.  Hoist the jit out of the "
                            "loop"))
                        break
                    cur = parents.get(cur)
            elif _call_name(node.func).split(".")[-1] == \
                    "lookup_program" and node.args:
                key = node.args[0]
                if isinstance(key, ast.Name):
                    scope = self._scope_of(node, parents)
                    key = key_exprs.get((scope, key.id), key)
                out.extend(self._check_key(ctx, key))
        return out

    def _check_key(self, ctx: FileCtx, key) -> List[Finding]:
        out: List[Finding] = []
        # displays/comprehensions immediately coerced hashable —
        # tuple(<genexp>) / frozenset([...]) — are the NORMAL key idiom
        coerced: Set[ast.AST] = set()
        for sub in ast.walk(key):
            if isinstance(sub, ast.Call) and _call_name(sub.func) in (
                    "tuple", "frozenset") and sub.args:
                coerced.add(sub.args[0])
        for sub in ast.walk(key):
            if sub in coerced:
                continue
            if isinstance(sub, (ast.List, ast.Set, ast.Dict,
                                ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                out.append(ctx.finding(
                    self.name, sub,
                    "unhashable value (list/set/dict display) inside a "
                    "program cache key — the dispatch-stability lookup "
                    "raises TypeError or, tuple()-coerced elsewhere, "
                    "drifts.  Use tuples of hashables"))
            elif isinstance(sub, ast.Call) and \
                    _call_name(sub.func) == "id":
                out.append(ctx.finding(
                    self.name, sub,
                    "id(...) inside a program cache key — a recycled "
                    "address aliases a NEW object onto a dead entry's "
                    "compiled program (the _PLAN_UID incident).  Use a "
                    "process-unique counter stamped on the object"))
        return out


# ---------------------------------------------------------------------------
# 9. gate-hygiene (ISSUE 15)
# ---------------------------------------------------------------------------
class GateHygieneChecker:
    """Every documented ``MXNET_*=0`` kill-switch must reduce its hooks
    to ONE module-global boolean test before any other work — the
    overhead contract PRs 1 (metrics), 8 (flight), 9 (memory ledger),
    12 (supervise) and 13 (introspect) each re-promised in prose; this
    rule machine-checks it.

    A gate is a module-level ``ENABLED = getenv("MXNET_...", ...)``.
    Two violation shapes:

      * **buried guard** — a function whose body contains the
        early-return guard (``if not ENABLED: return``) anywhere but
        as its first statement, with effectful work (calls, control
        flow) before it: the disabled path no longer costs one boolean
        test;
      * **per-call env re-read** — a function body re-reading the
        gate's env var through ``getenv``/``os.environ`` instead of
        testing the module global: an env lookup + string parse per
        call on a path the contract says costs one flag test (and a
        mid-run ``export`` silently half-toggles the subsystem —
        enable()/disable() and the global stay authoritative).
    """

    name = "gate-hygiene"

    def __init__(self):
        # env var -> (module relpath) for every gate seen this run
        self._gates: Dict[str, str] = {}
        # (relpath, lineno, col, symbol-less env, suppressed) of
        # in-function getenv reads, resolved in finalize once every
        # module's gates are known.  Primitives only — holding the
        # FileCtx here would pin every swept file's source + AST in
        # memory for the whole run
        self._fn_reads: List[Tuple[str, int, int, str, bool]] = []

    @staticmethod
    def _gate_env(node) -> Optional[str]:
        """Env name when ``node`` is ``ENABLED = getenv("MXNET_X", ..)``
        (bool()-wrapped and AnnAssign forms included)."""
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
            value = node.value
        else:
            return None
        if not any(isinstance(t, ast.Name) and t.id == "ENABLED"
                   for t in targets):
            return None
        if isinstance(value, ast.Call) and \
                _call_name(value.func) == "bool" and value.args:
            value = value.args[0]
        if isinstance(value, ast.Call) and \
                _call_name(value.func).split(".")[-1] in (
                    "getenv", "get") and value.args:
            name = _const_str(value.args[0])
            if name and _ENV_RE.match(name):
                return name
        return None

    @staticmethod
    def _is_gate_guard(stmt, gate_names: Set[str]) -> bool:
        """``if not ENABLED: return/yield/pass`` (possibly
        ``not ENABLED or ...``) at statement level."""
        if not isinstance(stmt, ast.If):
            return False
        test = stmt.test
        candidates = [test]
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.Or):
            candidates = list(test.values)
        hit = False
        for c in candidates:
            if isinstance(c, ast.UnaryOp) and isinstance(c.op, ast.Not):
                inner = c.operand
                key = inner.attr if isinstance(inner, ast.Attribute) \
                    else inner.id if isinstance(inner, ast.Name) else ""
                if key in gate_names:
                    hit = True
        if not hit:
            return False
        return all(isinstance(s, (ast.Return, ast.Pass, ast.Expr))
                   for s in stmt.body)

    @staticmethod
    def _effectful(stmt) -> bool:
        """Work the disabled path would pay before reaching the guard."""
        if isinstance(stmt, (ast.With, ast.For, ast.While, ast.Try)):
            return True
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                return True
        return False

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        gate_envs: Dict[str, str] = {}
        for stmt in ctx.tree.body:
            env = self._gate_env(stmt)
            if env:
                gate_envs[env] = "ENABLED"
                self._gates[env] = ctx.relpath
        out: List[Finding] = []
        gate_names = {"ENABLED"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # record in-function env re-reads for finalize
                for sub in ast.walk(node):
                    name = EnvVarSyncChecker._read_name(sub)
                    if name:
                        ln = getattr(sub, "lineno", 0)
                        self._fn_reads.append(
                            (ctx.relpath, ln,
                             getattr(sub, "col_offset", 0), name,
                             ctx.suppressed(self.name, ln)))
                if not gate_envs:
                    continue
                body = node.body
                start = 0
                if body and isinstance(body[0], ast.Expr) and \
                        isinstance(body[0].value, ast.Constant):
                    start = 1  # docstring
                for i, stmt in enumerate(body):
                    if not self._is_gate_guard(stmt, gate_names):
                        continue
                    if i == start:
                        break
                    if any(self._effectful(p) for p in body[start:i]):
                        out.append(ctx.finding(
                            self.name, stmt,
                            f"kill-switch guard 'if not ENABLED' is "
                            f"buried behind other work in "
                            f"'{node.name}' — the disabled path must "
                            f"cost ONE module-global boolean test "
                            f"(move the guard to the first "
                            f"statement)"))
                    break
        return out

    def finalize(self) -> List[Finding]:
        out: List[Finding] = []
        reported: Set[Tuple[str, int]] = set()
        for relpath, line, col, env, suppressed in self._fn_reads:
            gate_mod = self._gates.get(env)
            if gate_mod is None or suppressed:
                continue
            where = (relpath, line)
            if where in reported:
                continue
            reported.add(where)
            out.append(Finding(
                rule=self.name, path=relpath, line=line, col=col,
                message=f"'{env}' is re-read from the environment "
                        f"inside a function, but it is the "
                        f"module-global kill-switch gate of "
                        f"{gate_mod} — test that module's ENABLED "
                        f"flag instead (one boolean test; env is "
                        f"parsed once at import)"))
        return out


# ---------------------------------------------------------------------------
# 10. bench-emit (ISSUE 15 satellite)
# ---------------------------------------------------------------------------
class BenchEmitChecker:
    """Every bench.py rider's result dict must be reachable from
    ``_emit``'s BENCH JSON — the exact omission fixed twice already
    (PR 12: the wholestep rider ran but never reached the artifact;
    PR 14: same for the mfu rider).  A rider that runs and reports
    nothing is worse than one that fails: the scoring artifact silently
    loses the axis.

    Checks any scanned ``bench*.py``, and — via ``finalize`` — always
    the repo's own ``bench.py`` even when the sweep paths don't include
    it: every string key K with a ``_STATE[K] = ...`` assignment must
    be READ (``_STATE[K]`` / ``_STATE.get(K)``) inside ``_emit``.
    """

    name = "bench-emit"

    def __init__(self):
        self._saw_repo_bench = False

    def check_file(self, ctx: FileCtx) -> List[Finding]:
        base = os.path.basename(ctx.relpath)
        if not (base.startswith("bench") and base.endswith(".py")):
            return []
        if ctx.relpath == "bench.py":
            self._saw_repo_bench = True
        return self._check_tree(ctx)

    def _check_tree(self, ctx: FileCtx) -> List[Finding]:
        emit_fn = None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "_emit":
                emit_fn = node
                break
        if emit_fn is None:
            return []

        def state_key(node) -> Optional[str]:
            # _STATE["k"] subscript
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "_STATE":
                sl = node.slice
                if isinstance(sl, ast.Index):  # py<3.9 compat shape
                    sl = sl.value
                return _const_str(sl)
            return None

        emitted: Set[str] = set()
        for node in ast.walk(emit_fn):
            k = state_key(node)
            if k:
                emitted.add(k)
            if isinstance(node, ast.Call) and \
                    _call_name(node.func) == "_STATE.get" and node.args:
                k = _const_str(node.args[0])
                if k:
                    emitted.add(k)
        out: List[Finding] = []
        seen: Set[str] = set()
        for node in ast.walk(ctx.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                k = state_key(t)
                if k and k not in emitted and k not in seen:
                    seen.add(k)
                    out.append(ctx.finding(
                        self.name, t,
                        f"rider result _STATE[{k!r}] is assigned but "
                        f"never read inside _emit — it will not reach "
                        f"the BENCH JSON artifact (the PR 12/PR 14 "
                        f"omission class).  Add an `out[{k!r}] = "
                        f"_STATE[{k!r}]` leg to _emit"))
        return out

    def finalize(self) -> List[Finding]:
        if self._saw_repo_bench:
            return []
        path = os.path.join(REPO_ROOT, "bench.py")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            return []
        ctx = FileCtx(path, "bench.py", source, tree)
        out = []
        for f in self._check_tree(ctx):
            if not ctx.suppressed(self.name, f.line):
                out.append(f)
        return out


# ---------------------------------------------------------------------------
def registry() -> Dict[str, type]:
    return {
        ThreadSafetyChecker.name: ThreadSafetyChecker,
        HostSyncChecker.name: HostSyncChecker,
        AtomicWriteChecker.name: AtomicWriteChecker,
        EnvVarSyncChecker.name: EnvVarSyncChecker,
        MetricsHygieneChecker.name: MetricsHygieneChecker,
        MemoryHygieneChecker.name: MemoryHygieneChecker,
        UseAfterDonateChecker.name: UseAfterDonateChecker,
        RetraceHazardChecker.name: RetraceHazardChecker,
        GateHygieneChecker.name: GateHygieneChecker,
        BenchEmitChecker.name: BenchEmitChecker,
    }


ALL_RULES = tuple(registry())
