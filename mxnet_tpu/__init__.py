"""mxnet_tpu — a TPU-native deep-learning framework with MXNet v1.0 capabilities.

A ground-up rebuild of the Apache MXNet v1.0 feature surface (reference:
/root/reference) designed for TPU: every operator lowers to XLA via JAX,
graphs compile whole (the XLA compiler replaces the NNVM GraphExecutor's
memory planner/scheduler), autograd rides jax.vjp, and distributed training
uses XLA collectives over an ICI device mesh (`KVStore('tpu_sync')`) instead
of NCCL/ps-lite.

Public surface mirrors `python/mxnet/__init__.py` in the reference:
  mx.nd, mx.sym, mx.mod, mx.gluon, mx.kv, mx.io, mx.autograd, mx.metric,
  mx.optimizer, mx.initializer, mx.context (cpu/gpu/tpu), mx.random, ...
"""

__version__ = "1.0.0.tpu0"

from . import base
from .base import MXNetError
from . import context
from .context import Context, current_context, cpu, gpu, tpu
from . import engine
from . import ops  # registers all operators
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import random
from . import autograd
from . import attribute
from .attribute import AttrScope
from . import name
from .name import NameManager
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import executor

from . import initializer
from .initializer import init
from . import optimizer
from .optimizer import Optimizer
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from . import recordio
from . import registry
from . import kvstore as kv
from .kvstore import KVStore
from . import model
from . import operator
from . import module
from . import module as mod
from . import parallel
from . import gluon
from . import observability
from . import analysis
from . import faultinject
from . import resilience
from . import profiler
from . import monitor
from . import monitor as mon
from .monitor import Monitor
from . import visualization as viz
from . import test_utils
from . import rnn
from . import image
from . import rtc
from . import contrib
from . import predictor
from . import serving
from . import checkpoint
from . import export
