"""Dependency engine facade over PJRT async dispatch.

Reference parity: `src/engine/` (SURVEY.md §2.1) — the reference hand-built an
async dataflow scheduler (ThreadedEnginePerDevice, ThreadedVar read/write
queues, OprBlock wait counters) because CUDA kernel launches needed explicit
ordering across streams.  On TPU, PJRT *is* that engine: every jax op enqueues
asynchronously and returns a future-backed Array; data dependencies order
execution; `Array.block_until_ready()` is WaitToRead.  This module keeps the
reference's user-visible Engine API (WaitForVar/WaitForAll, bulking, naive
mode) as a thin layer so code written against `mx.engine` semantics runs
unmodified.

Engine types (parity: src/engine/engine.cc:32-48, MXNET_ENGINE_TYPE):
  - 'ThreadedEnginePerDevice' / 'ThreadedEnginePooled': PJRT async dispatch
    (the default; names retained for compatibility).
  - 'NaiveEngine': synchronous debugging mode — every op blocks until done,
    serializing execution exactly like the reference's NaiveEngine
    (src/engine/naive_engine.cc:36).
"""
from __future__ import annotations

import contextlib

import jax

from .base import getenv

_engine_type = getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
_bulk_size = 0


def engine_type() -> str:
    return _engine_type


def set_engine_type(name: str) -> None:
    global _engine_type
    _engine_type = name


def is_naive() -> bool:
    return _engine_type == "NaiveEngine"


def maybe_sync(arrays) -> None:
    """In NaiveEngine mode, block on the given jax arrays (debug serialization)."""
    if _engine_type == "NaiveEngine":
        for a in arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()


def wait_for_var(array) -> None:
    """Parity: Engine::WaitForVar — block until this buffer is computed."""
    if hasattr(array, "block_until_ready"):
        array.block_until_ready()


def wait_for_all() -> None:
    """Parity: Engine::WaitForAll / mx.nd.waitall.

    PJRT has no global barrier; jax.effects_barrier() drains pending effects
    and live arrays synchronize on access, so this blocks host-side work.
    """
    try:
        jax.effects_barrier()
    except Exception:
        pass


def set_bulk_size(size: int) -> int:
    """Parity: Engine::set_bulk_size (include/mxnet/engine.h:283).

    On TPU, op bulking = XLA fusion under jit; this knob is retained for API
    compatibility and returns the previous value.
    """
    global _bulk_size
    old, _bulk_size = _bulk_size, size
    return old


@contextlib.contextmanager
def bulk(size: int):
    old = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(old)
