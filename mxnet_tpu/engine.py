"""Dependency engine facade over PJRT async dispatch.

Reference parity: `src/engine/` (SURVEY.md §2.1) — the reference hand-built an
async dataflow scheduler (ThreadedEnginePerDevice, ThreadedVar read/write
queues, OprBlock wait counters) because CUDA kernel launches needed explicit
ordering across streams.  On TPU, PJRT *is* that engine: every jax op enqueues
asynchronously and returns a future-backed Array; data dependencies order
execution; `Array.block_until_ready()` is WaitToRead.  This module keeps the
reference's user-visible Engine API (WaitForVar/WaitForAll, bulking, naive
mode) as a thin layer so code written against `mx.engine` semantics runs
unmodified.

Engine types (parity: src/engine/engine.cc:32-48, MXNET_ENGINE_TYPE):
  - 'ThreadedEnginePerDevice' / 'ThreadedEnginePooled': PJRT async dispatch
    (the default; names retained for compatibility).
  - 'NaiveEngine': synchronous debugging mode — every op blocks until done,
    serializing execution exactly like the reference's NaiveEngine
    (src/engine/naive_engine.cc:36).
"""
from __future__ import annotations

import contextlib
import time

import jax

from .analysis import sanitizer as _sanitizer
from .base import getenv
from .observability import metrics as _metrics

_engine_type = getenv("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
_bulk_size = 0


def engine_type() -> str:
    return _engine_type


def set_engine_type(name: str) -> None:
    global _engine_type
    _engine_type = name


def is_naive() -> bool:
    return _engine_type == "NaiveEngine"


def maybe_sync(arrays) -> None:
    """In NaiveEngine mode, block on the given jax arrays (debug serialization)."""
    if _engine_type == "NaiveEngine":
        for a in arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()


def wait_for_var(array) -> None:
    """Parity: Engine::WaitForVar — block until this buffer is computed."""
    if hasattr(array, "block_until_ready"):
        _sanitizer.check_sync("engine.wait_for_var")
        on = _metrics.ENABLED  # captured once: an enable() mid-wait must
        t0 = time.perf_counter() if on else 0.0  # not record t0=0.0
        array.block_until_ready()
        if on:
            _metrics.ENGINE_WAITS.inc(kind="wait_for_var")
            _metrics.ENGINE_WAIT_SECONDS.inc(time.perf_counter() - t0)


def wait_for_all() -> None:
    """Parity: Engine::WaitForAll / mx.nd.waitall.

    PJRT has no global barrier; jax.effects_barrier() drains pending effects
    and live arrays synchronize on access, so this blocks host-side work.
    """
    _sanitizer.check_sync("engine.wait_for_all")
    on = _metrics.ENABLED  # captured once: an enable() mid-wait must not
    t0 = time.perf_counter() if on else 0.0  # record t0=0.0
    try:
        jax.effects_barrier()
    except Exception:
        pass
    from ._native import lib_if_loaded
    l = lib_if_loaded()  # never trigger a native build inside a barrier
    if l is not None:
        l.MXTEngineWaitAll()
    if on:
        _metrics.ENGINE_WAITS.inc(kind="wait_for_all")
        _metrics.ENGINE_WAIT_SECONDS.inc(time.perf_counter() - t0)


def set_bulk_size(size: int) -> int:
    """Parity: Engine::set_bulk_size (include/mxnet/engine.h:283).

    On TPU, op bulking = XLA fusion under jit; this knob is retained for API
    compatibility and returns the previous value.
    """
    global _bulk_size
    old, _bulk_size = _bulk_size, size
    return old


@contextlib.contextmanager
def bulk(size: int):
    old = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(old)


# ---------------------------------------------------------------------------
# Native host engine (src/runtime/engine.cc): async scheduling for HOST work
# (IO, checkpoint writes, metric sinks) with the reference's read/write var
# discipline.  Device compute stays on PJRT; this orders what PJRT can't see.
# ---------------------------------------------------------------------------
_native_keepalive = []


def _native():
    from ._native import lib
    return lib()


def native_available() -> bool:
    return _native() is not None


class HostVar:
    """Engine variable (parity: Engine::NewVariable, engine.h:134)."""

    def __init__(self):
        l = _native()
        self._lib = l
        self.handle = l.MXTEngineNewVar() if l is not None else None

    def __del__(self):
        if getattr(self, "handle", None) is not None:
            self._lib.MXTEngineDeleteVar(self.handle)
            self.handle = None


def push_host(fn, read_vars=(), write_vars=(), priority=0) -> None:
    """Parity: Engine::PushAsync for host callbacks.

    fn() runs on a native worker thread once all deps clear; concurrent
    reads, exclusive writes, push order preserved per var.  Without the
    native lib (or in NaiveEngine mode) fn runs synchronously.
    """
    l = _native()
    if l is None or is_naive():
        fn()
        return
    import ctypes

    cb_type = ctypes.CFUNCTYPE(None, ctypes.c_void_p)

    def trampoline(_):
        try:
            fn()
        finally:
            _native_keepalive.remove(cb)

    cb = cb_type(trampoline)
    _native_keepalive.append(cb)
    n_r, n_w = len(read_vars), len(write_vars)
    rv = (ctypes.c_uint64 * max(n_r, 1))(*[v.handle for v in read_vars])
    wv = (ctypes.c_uint64 * max(n_w, 1))(*[v.handle for v in write_vars])
    l.MXTEnginePushAsync(cb, None, rv, n_r, wv, n_w, priority)


def wait_for_host_var(var: HostVar) -> None:
    l = _native()
    if l is not None and var.handle is not None:
        l.MXTEngineWaitForVar(var.handle)


def wait_host_all() -> None:
    l = _native()
    if l is not None:
        l.MXTEngineWaitAll()
