"""Test utilities (parity: python/mxnet/test_utils.py, 1,571 LoC).

The reference's op-test machinery: assert_almost_equal, finite-difference
check_numeric_gradient (:789), check_symbolic_forward/backward (:921,995),
rand_ndarray, default_context, and check_consistency (:1203) — re-targeted
as CPU-vs-TPU (instead of CPU-vs-GPU) cross-backend equivalence.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context
from . import io
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol as sym
from . import random as _random

_rng = _np.random.RandomState(1234)


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context) -> None:
    Context.default_ctx = ctx


def default_dtype():
    return _np.float32


def get_atol(atol=None):
    return 1e-20 if atol is None else atol


def get_rtol(rtol=None):
    return 1e-5 if rtol is None else rtol


def random_arrays(*shapes):
    arrays = [_np.array(_np.random.randn(), dtype=default_dtype())
              if len(s) == 0 else
              _np.random.randn(*s).astype(default_dtype()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def random_sample(population, k):
    population_copy = population[:]
    _np.random.shuffle(population_copy)
    return population_copy[0:k]


def rand_shape_2d(dim0=10, dim1=10):
    return _rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1)


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1),
            _rng.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution=None):
    """Parity: test_utils.rand_ndarray incl. sparse storage types."""
    if stype == "default":
        return nd.array(random_arrays(shape), dtype=dtype)
    density = 0.1 if density is None else density
    dense = _np.random.randn(*shape).astype(dtype or "float32")
    mask = _np.random.rand(*shape) < density
    dense = dense * mask
    from .ndarray import sparse
    if stype == "row_sparse":
        return sparse.row_sparse_array(dense)
    if stype == "csr":
        return sparse.csr_matrix(dense)
    raise MXNetError(f"unknown storage type {stype}")


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    rtol, atol = get_rtol(rtol), get_atol(atol)
    diff = _np.abs(a - b)
    tol = atol + rtol * _np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = _np.argmax(violation)
    idx = _np.unravel_index(loc, violation.shape)
    return idx, _np.max(violation)


def same(a, b):
    return _np.array_equal(a, b)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Parity: test_utils.assert_almost_equal (:467)."""
    a = a.asnumpy() if isinstance(a, NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else _np.asarray(b)
    rtol, atol = get_rtol(rtol), get_atol(atol)
    if _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    index, rel = find_max_violation(a, b, rtol, atol)
    raise AssertionError(
        f"Error {rel} exceeds tolerance rtol={rtol}, atol={atol}. "
        f"Location of maximum error: {index}, "
        f"{names[0]}={a[index]:.8f}, {names[1]}={b[index]:.8f}")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    return _np.allclose(a, b, rtol=get_rtol(rtol), atol=get_atol(atol),
                        equal_nan=equal_nan)


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
        assert False
    except exception_type:
        return


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    inputs = {k: nd.array(v) for k, v in inputs.items()}
    exe = sym_.bind(ctx, args=inputs)
    exe.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym_, location, ctx, dtype=None):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(sym_.list_arguments()):
            raise ValueError(
                f"Symbol arguments and keys of the given location do not "
                f"match. symbol args: {sym_.list_arguments()}, location.keys():"
                f" {list(location.keys())}")
    else:
        location = {k: v for k, v in zip(sym_.list_arguments(), location)}
    location = {k: nd.array(v, ctx=ctx, dtype=v.dtype if dtype is None
                            else dtype)
                if isinstance(v, _np.ndarray) else
                (v if isinstance(v, NDArray) else nd.array(v, ctx=ctx))
                for k, v in location.items()}
    return location


def _parse_aux_states(sym_, aux_states, ctx, dtype=None):
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        if set(aux_states.keys()) != set(sym_.list_auxiliary_states()):
            raise ValueError("Symbol aux_states names and given aux_states "
                             "do not match.")
    elif isinstance(aux_states, (list, tuple)):
        aux_names = sym_.list_auxiliary_states()
        aux_states = {k: v for k, v in zip(aux_names, aux_states)}
    return {k: nd.array(v, ctx=ctx) if not isinstance(v, NDArray) else v
            for k, v in aux_states.items()}


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients via central differences."""
    approx_grads = {k: _np.zeros(v.shape, dtype=_np.float32)
                    for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(_np.prod(old_value.shape))):
            idx = _np.unravel_index(i, old_value.shape)
            # forward perturbed +eps
            loc_p = old_value.copy()
            loc_p[idx] += eps
            executor.arg_dict[k][:] = loc_p
            f_peps = executor.forward(is_train=use_forward_train)[0].asnumpy().sum()
            loc_m = old_value.copy()
            loc_m[idx] -= eps
            executor.arg_dict[k][:] = loc_m
            f_meps = executor.forward(is_train=use_forward_train)[0].asnumpy().sum()
            approx_grads[k][idx] = (f_peps - f_meps) / (2 * eps)
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym_, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True, ctx=None,
                           grad_stype_dict=None, dtype=_np.float64):
    """Finite-difference gradient checking (parity: test_utils.py:789).

    Note: runs in float32 (TPU-native default); tolerances follow the
    reference's float32-path defaults.
    """
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx)
    location_np = {k: v.asnumpy() for k, v in location.items()}
    aux = _parse_aux_states(sym_, aux_states, ctx)

    if grad_nodes is None:
        grad_nodes = [k for k in sym_.list_arguments()]
    elif isinstance(grad_nodes, dict):
        grad_nodes = list(grad_nodes.keys())

    # random projection to scalar so we check d(proj.out)/d(arg)
    out = sym_
    proj_shape = sym_.infer_shape(
        **{k: v.shape for k, v in location_np.items()})[1][0]
    proj = _np.random.uniform(-1, 1, size=proj_shape).astype(_np.float32)

    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in sym_.list_arguments()}
    exe = sym_.bind(ctx, args=location,
                    args_grad={k: nd.zeros(location[k].shape, ctx=ctx)
                               for k in grad_nodes},
                    grad_req=grad_req, aux_states=aux)
    exe.forward(is_train=True)
    exe.backward(out_grads=[nd.array(proj, ctx=ctx)])
    symbolic_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    # numeric: perturb each entry, objective = sum(out * proj)
    fwd_exe = sym_.bind(ctx, args={k: v.copy() for k, v in location.items()},
                        aux_states={k: v.copy() for k, v in aux.items()})

    def objective():
        return float((fwd_exe.forward(
            is_train=use_forward_train)[0].asnumpy() * proj).sum())

    for name in grad_nodes:
        base = location_np[name].astype(_np.float64)
        approx = _np.zeros_like(base)
        it = _np.nditer(base, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            pert = base.copy()
            pert[idx] += numeric_eps
            fwd_exe.arg_dict[name][:] = pert.astype(_np.float32)
            fp = objective()
            pert[idx] -= 2 * numeric_eps
            fwd_exe.arg_dict[name][:] = pert.astype(_np.float32)
            fm = objective()
            approx[idx] = (fp - fm) / (2 * numeric_eps)
            it.iternext()
        fwd_exe.arg_dict[name][:] = base.astype(_np.float32)
        assert_almost_equal(approx, symbolic_grads[name], rtol,
                            atol if atol is not None else 1e-4,
                            (f"NUMERICAL_{name}", f"BACKWARD_{name}"))


def check_symbolic_forward(sym_, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=None):
    """Parity: test_utils.py:921."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx, dtype=dtype)
    aux = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym_.list_outputs()]
    exe = sym_.bind(ctx, args=location, aux_states=aux)
    outputs = exe.forward(is_train=False)
    for output_name, expect, output in zip(sym_.list_outputs(), expected,
                                           outputs):
        assert_almost_equal(expect, output.asnumpy(), rtol, atol or 1e-5,
                            ("EXPECTED_%s" % output_name,
                             "FORWARD_%s" % output_name),
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym_, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, grad_stypes=None, equal_nan=False,
                            dtype=None):
    """Parity: test_utils.py:995."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx, dtype=dtype)
    aux = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym_.list_arguments(), expected)}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in sym_.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(sym_.list_arguments(), grad_req)}
    args_grad = {k: nd.zeros(location[k].shape, ctx=ctx)
                 for k in expected if grad_req.get(k, "null") != "null"}
    # 'add' semantics: preload random values
    adds = {}
    for k, req in grad_req.items():
        if req == "add" and k in args_grad:
            adds[k] = _np.random.normal(
                size=location[k].shape).astype(_np.float32)
            args_grad[k][:] = adds[k]
    exe = sym_.bind(ctx, args=location, args_grad=args_grad,
                    grad_req=grad_req, aux_states=aux)
    exe.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [nd.array(v, ctx=ctx) if not isinstance(v, NDArray) else v
                     for v in out_grads]
    elif isinstance(out_grads, dict):
        out_grads = [nd.array(out_grads[k], ctx=ctx)
                     for k in sym_.list_outputs()]
    exe.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in exe.grad_dict.items()}
    for name in expected:
        if grad_req.get(name, "null") == "write":
            assert_almost_equal(expected[name], grads[name], rtol,
                                atol or 1e-6,
                                (f"EXPECTED_{name}", f"BACKWARD_{name}"),
                                equal_nan=equal_nan)
        elif grad_req.get(name) == "add":
            assert_almost_equal(expected[name] + adds[name],
                                grads[name], rtol, atol or 1e-6,
                                (f"EXPECTED_{name}", f"BACKWARD_{name}"),
                                equal_nan=equal_nan)
    return grads


def check_consistency(sym_, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None, equal_nan=False,
                      report=None):
    """Cross-backend equivalence (parity: test_utils.py:1203 — the reference
    compared cpu vs gpu; here cpu vs tpu/accelerator ctx lists)."""
    tol = tol or {_np.dtype(_np.float16): 1e-1, _np.dtype(_np.float32): 1e-3,
                  _np.dtype(_np.float64): 1e-5, _np.dtype(_np.uint8): 0,
                  _np.dtype(_np.int32): 0}
    if isinstance(tol, float):
        tol = {_np.dtype(d): tol for d in
               (_np.float16, _np.float32, _np.float64, _np.uint8, _np.int32)}
    assert len(ctx_list) > 1
    if isinstance(sym_, sym.Symbol):
        sym_ = [sym_] * len(ctx_list)

    output_points = []
    for s, ctx in zip(sym_, ctx_list):
        ctx_spec = dict(ctx)
        context = ctx_spec.pop("ctx")
        type_dict = ctx_spec.pop("type_dict", {})
        exe = s.simple_bind(context, grad_req=grad_req, type_dict=type_dict,
                            **ctx_spec)
        if arg_params:
            for k, v in arg_params.items():
                exe.arg_dict[k][:] = v
        else:
            if not output_points:
                for name, arr in exe.arg_dict.items():
                    arr[:] = _np.random.normal(
                        size=arr.shape, scale=scale).astype(_np.float32)
                arg_params = {k: v.asnumpy() for k, v in exe.arg_dict.items()}
            else:
                for k, v in arg_params.items():
                    exe.arg_dict[k][:] = v
        if aux_params:
            for k, v in aux_params.items():
                exe.aux_dict[k][:] = v
        exe.forward(is_train=grad_req != "null")
        output_points.append([o.asnumpy() for o in exe.outputs])

    dtypes = [o.dtype for o in output_points[0]]
    gt = ground_truth or output_points[0]
    for i, outs in enumerate(output_points[1:], 1):
        for j, (g, o) in enumerate(zip(gt, outs)):
            # kind 'f' misses ml_dtypes floats (bfloat16 is kind 'V') —
            # exactly the dtypes the TPU consistency tier audits
            if report is not None and (g.dtype.kind == "f"
                                       or "float" in g.dtype.name):
                report["max_err"] = max(
                    report.get("max_err", 0.0),
                    float(_np.max(_np.abs(_np.asarray(g, _np.float64) -
                                          _np.asarray(o, _np.float64)))))
            try:
                assert_almost_equal(g, o, rtol=tol[_np.dtype(dtypes[j])],
                                    atol=tol[_np.dtype(dtypes[j])],
                                    equal_nan=equal_nan)
            except AssertionError:
                if raise_on_err:
                    raise
    return gt


def discard_stderr(*args, **kwargs):
    import contextlib
    import io
    return contextlib.redirect_stderr(io.StringIO())


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def download(url, fname=None, dirname=None, overwrite=False):
    from .gluon.utils import download as _dl
    return _dl(url, fname or dirname, overwrite)


def get_mnist(num_train=600, num_test=100):
    """Synthetic MNIST-shaped dataset when real files are unavailable
    (zero-egress environments).  LEARNABLE: each class is a fixed smooth
    prototype image plus noise, so classifiers trained on it reach high
    accuracy and demos (adversarial examples, multi-task, fine-tuning)
    behave like they do on the real data."""
    rs = _np.random.RandomState(42)
    # smooth per-class prototypes (low-freq random fields, blurred)
    protos = rs.rand(10, 1, 32, 32).astype(_np.float32)
    k = _np.ones(5, _np.float32) / 5.0  # separable box blur
    blurred = []
    for p in protos:
        img = p[0]
        for _ in range(2):
            img = _np.stack([
                _np.convolve(row, k, mode="same") for row in img])
            img = _np.stack([
                _np.convolve(col, k, mode="same") for col in img.T]).T
        blurred.append(img[2:30, 2:30])
    protos = _np.stack(blurred)[:, None]          # (10,1,28,28)
    protos = (protos - protos.min()) / (_np.ptp(protos) + 1e-9)

    def make(n):
        y = rs.randint(0, 10, n)
        x = protos[y] + rs.normal(0, 0.25, (n, 1, 28, 28))
        return x.clip(0, 1).astype(_np.float32), y.astype(_np.float32)

    train_x, train_y = make(num_train)
    test_x, test_y = make(num_test)
    return {"train_data": train_x, "train_label": train_y,
            "test_data": test_x, "test_label": test_y}


# ---------------------------------------------------------------------------
# Golden-logit zoo fixtures (VERDICT r3 #2; parity:
# tests/python/gpu/test_forward.py — committed expected logits pin the
# model zoo against silent numeric drift).  Params and inputs are
# regenerated deterministically from fixed seeds (jax PRNG + numpy
# RandomState), so the committed .npz holds only the tiny logits block.
# ---------------------------------------------------------------------------
def golden_model_cases():
    """name -> zero-arg builder returning (net, input NDArray).  Shared by
    tools/make_golden.py (writer), tests/test_golden_forward.py (CPU
    gate) and tools/run_tpu_consistency.py (on-chip check)."""
    from . import nd as _nd
    from . import random as _random
    from . import initializer as _init
    from .gluon.model_zoo import vision as _vision
    from .gluon.model_zoo.transformer import TransformerLM as _TLM

    def _vision_case(factory, shape=(2, 3, 64, 64)):
        def build():
            _random.seed(0)
            net = factory()
            net.initialize(_init.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2))
            rs = _np.random.RandomState(42)
            x = _nd.array(rs.normal(0, 1, shape).astype(_np.float32))
            return net, x
        return build

    def _lm_case():
        def build():
            _random.seed(0)
            net = _TLM(vocab=32, dim=32, num_layers=2, num_heads=4,
                       max_len=16)
            net.initialize(_init.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2))
            rs = _np.random.RandomState(42)
            x = _nd.array(rs.randint(0, 32, (2, 16)).astype(_np.float32))
            return net, x
        return build

    return {
        "resnet18_v1": _vision_case(_vision.resnet18_v1),
        "resnet18_v2": _vision_case(_vision.resnet18_v2),
        "mobilenet0_25": _vision_case(_vision.mobilenet0_25),
        "squeezenet1_0": _vision_case(_vision.squeezenet1_0),
        # densenet's final AvgPool2D(7) assumes the 224 input contract
        "densenet121": _vision_case(_vision.densenet121,
                                    shape=(1, 3, 224, 224)),
        # inception's branchy concat tree is the whole-graph NHWC
        # pass's hardest shape (channel-axis Concat stays CL); 299 is
        # its input contract
        "inception_v3": _vision_case(_vision.inception_v3,
                                     shape=(1, 3, 299, 299)),
        "alexnet": _vision_case(_vision.alexnet,
                                shape=(2, 3, 224, 224)),
        "transformer_lm": _lm_case(),
    }


def golden_forward(name):
    """Deterministic logits for one golden case (inference mode)."""
    net, x = golden_model_cases()[name]()
    out = net(x)
    return _np.asarray(out.asnumpy(), _np.float32)


def golden_fixture_path(name):
    import os as _os
    return _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "tests", "golden",
        f"{name}.npz")


# -- reference test_utils closure (round-4 API audit) -----------------------

def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        distribution=None, data_init=None,
                        rsp_indices=None, modifier_func=None,
                        shuffle_csr_indices=False):
    """Random sparse NDArray (parity: test_utils.rand_sparse_ndarray —
    returns (arr, aux) with aux = (vals, idx) for rsp, (data, indices,
    indptr) for csr).  distribution: 'uniform' (default) or 'powerlaw'
    (csr only — geometrically decaying per-row nnz, the reference's
    skewed-structure generator)."""
    density = 0.1 if density is None else density
    dtype = dtype or "float32"
    if distribution not in (None, "uniform", "powerlaw"):
        raise MXNetError(f"unsupported distribution {distribution!r}")
    from .ndarray import sparse
    if stype == "row_sparse":
        if distribution == "powerlaw":
            raise MXNetError("powerlaw distribution is csr-only")
        if rsp_indices is not None:
            idx = _np.asarray(rsp_indices, _np.int64)
        else:
            n = max(1, int(round(shape[0] * density)))
            idx = _np.sort(_np.random.choice(shape[0], n, replace=False))
        vals = _np.random.randn(len(idx), *shape[1:]).astype(dtype)
        if data_init is not None:
            vals[:] = data_init
        if modifier_func is not None and vals.size:
            vals = _np.vectorize(modifier_func)(vals).astype(dtype)
        arr = sparse.row_sparse_array((vals, idx), shape=shape, dtype=dtype)
        return arr, (vals, idx)
    if stype == "csr":
        if distribution == "powerlaw":
            # Reference semantics (test_utils.py:164-210): exponentially
            # INCREASING per-row occupancy — every row is first seeded at
            # column 0 (so no row is empty), then row i fills columns
            # 1..min(2^(i+1), ncols) until the nnz budget is spent;
            # values are 1 + U(0.001, 2).  Requires nnz >= 2*nrows.
            total = int(shape[0] * shape[1] * density)
            if total < 2 * shape[0]:
                raise MXNetError(
                    "powerlaw not supported for density %s at shape %s: "
                    "needs nrows*ncols*density >= 2*nrows"
                    % (density, (shape[0], shape[1])))
            dense = _np.zeros(shape, dtype)
            unused = total

            def _vals(n):
                return (1 + _np.random.uniform(0.001, 2, n)).astype(dtype)

            for i in range(shape[0]):
                if unused <= 0:
                    break
                dense[i, 0] = _vals(1)[0]
                unused -= 1
            col_max = 2
            for i in range(shape[0]):
                if unused <= 0:
                    break
                col_limit = min(shape[1], col_max)
                if col_limit == shape[1] and unused > col_limit:
                    dense[i, 1:] = _vals(shape[1] - 1)
                    unused -= col_limit - 1
                    continue
                n = min(col_limit - 1, unused)
                dense[i, 1:1 + n] = _vals(n)
                unused -= n
                col_max *= 2
            if unused > 0:
                raise MXNetError(
                    "powerlaw not supported for density %s at shape %s"
                    % (density, (shape[0], shape[1])))
        else:
            dense = _np.random.randn(*shape).astype(dtype)
            dense *= _np.random.rand(*shape) < density
        if data_init is not None:
            dense[dense != 0] = data_init
        if modifier_func is not None:
            nz = dense != 0
            if nz.any():
                dense[nz] = _np.vectorize(modifier_func)(dense[nz])
        arr = sparse.csr_matrix(nd.array(dense.astype(dtype)))
        if shuffle_csr_indices:
            arr = shuffle_csr_column_indices(arr)
        return arr, (arr.data.asnumpy(), arr.indices.asnumpy(),
                     arr.indptr.asnumpy())
    raise MXNetError(f"unknown storage type {stype}")


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=None, modifier_func=None, density=0.5,
                        shuffle_csr_indices=False):
    """Parity: test_utils.create_sparse_array."""
    arr, _ = rand_sparse_ndarray(shape, stype, density=density, dtype=dtype,
                                 data_init=data_init,
                                 rsp_indices=rsp_indices,
                                 modifier_func=modifier_func,
                                 shuffle_csr_indices=shuffle_csr_indices)
    return arr


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=None,
                           modifier_func=None, shuffle_csr_indices=False):
    """Sparse array generator admitting zero-density (parity:
    test_utils.create_sparse_array_zd)."""
    if stype == "row_sparse" and density == 0:
        rsp_indices = _np.array([], _np.int64)
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               modifier_func=modifier_func,
                               density=density,
                               shuffle_csr_indices=shuffle_csr_indices)


def shuffle_csr_column_indices(csr):
    """Permute column order within each CSR row (parity: tests feed
    unsorted-column CSRs to check kernels don't assume sorted cols)."""
    from .ndarray.sparse import CSRNDArray
    indptr = _np.asarray(csr.indptr.asnumpy())
    cols = _np.array(csr.indices.asnumpy())
    vals = _np.array(csr.data.asnumpy())
    for i in range(len(indptr) - 1):
        s, e = indptr[i], indptr[i + 1]
        p = _np.random.permutation(e - s)
        cols[s:e] = cols[s:e][p]
        vals[s:e] = vals[s:e][p]
    return CSRNDArray(vals, indptr, cols, csr.shape)


def almost_equal_ignore_nan(a, b, rtol=None, atol=None):
    """Parity: test_utils.almost_equal_ignore_nan — drop positions where
    EITHER side is NaN, compare the rest."""
    a = _np.copy(a)
    b = _np.copy(b)
    nan_mask = _np.logical_or(_np.isnan(a), _np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    return almost_equal(a, b, rtol, atol)


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=("a", "b")):
    a = _np.copy(a)
    b = _np.copy(b)
    nan_mask = _np.logical_or(_np.isnan(a), _np.isnan(b))
    a[nan_mask] = 0
    b[nan_mask] = 0
    assert_almost_equal(a, b, rtol, atol, names)


def same_array(array1, array2):
    """Whether two NDArrays share the same backing buffer (parity:
    test_utils.same_array's aliasing probe — functional buffers make
    identity the sharing criterion).  Sparse arrays rebuild their dense
    view per access, so only object identity can witness sharing."""
    if array1 is array2:
        return True
    if array1.shape != array2.shape:
        return False
    if array1.stype != "default" or array2.stype != "default":
        return False
    return array1._data is array2._data


def assign_each(the_input, function):
    """Elementwise python function application (parity: assign_each)."""
    arr = _np.array(the_input.asnumpy() if hasattr(the_input, "asnumpy")
                    else the_input)
    out = _np.vectorize(function)(arr) if function is not None else arr
    return nd.array(out.astype(arr.dtype))


def assign_each2(input1, input2, function):
    a = _np.array(input1.asnumpy() if hasattr(input1, "asnumpy")
                  else input1)
    b = _np.array(input2.asnumpy() if hasattr(input2, "asnumpy")
                  else input2)
    out = _np.vectorize(function)(a, b) if function is not None else a
    return nd.array(out.astype(a.dtype))


class DummyIter(io.DataIter):
    """Infinite repetition of the first batch of a real iterator —
    removes IO cost from op benchmarks (parity: test_utils.DummyIter,
    a DataIter so reset()-calling training loops work)."""

    def __init__(self, real_iter):
        super().__init__(real_iter.batch_size)
        self.real_iter = real_iter
        self._provide_data = real_iter.provide_data
        self._provide_label = real_iter.provide_label
        self.the_batch = next(iter(real_iter))

    @property
    def provide_data(self):
        return self._provide_data

    @property
    def provide_label(self):
        return self._provide_label

    def next(self):
        return self.the_batch


def check_speed(sym_, location=None, ctx=None, N=20, grad_req=None,
                typ="whole", **kwargs):
    """Mean seconds/iteration of forward(+backward) on a bound executor
    (parity: test_utils.check_speed)."""
    import time
    ctx = ctx or default_context()
    if typ not in ("whole", "forward"):
        raise MXNetError(f"typ must be 'whole' or 'forward', got {typ!r}")
    if grad_req is None:
        grad_req = "write" if typ == "whole" else "null"
    if location is None:
        shapes, _, _ = sym_.infer_shape(**kwargs)
        location = {k: _np.random.normal(0, 1, s).astype("float32")
                    for k, s in zip(sym_.list_arguments(), shapes)}
    exe = sym_.simple_bind(ctx=ctx, grad_req=grad_req,
                           **{k: v.shape for k, v in location.items()})
    for k, v in location.items():
        exe.arg_dict[k][:] = v
    # warmup (compile) then timed loop with one end sync
    if typ == "whole":
        exe.forward(is_train=True)
        exe.backward(out_grads=exe.outputs)
        exe.outputs[0].wait_to_read()
        tic = time.time()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward(out_grads=exe.outputs)
        _np.asarray(exe.outputs[0].asnumpy())
        return (time.time() - tic) / N
    exe.forward(is_train=False)
    exe.outputs[0].wait_to_read()
    tic = time.time()
    for _ in range(N):
        exe.forward(is_train=False)
    _np.asarray(exe.outputs[0].asnumpy())
    return (time.time() - tic) / N


def get_bz2_data(data_dir, data_name, url, data_origin_name):
    """Fetch+decompress a .bz2 dataset (parity: test_utils.get_bz2_data;
    on an egress-less pod an already-present archive is decompressed
    without network)."""
    import bz2
    import os
    path = os.path.join(data_dir, data_name)
    origin = os.path.join(data_dir, data_origin_name)
    if os.path.exists(path):
        return path
    if not os.path.exists(origin):
        download(url, fname=origin)
    # decompress to a same-dir tmp, then one os.replace: a crash
    # mid-decompress must not leave a torn file that the
    # os.path.exists fast path above would trust forever after
    tmp = f"{path}.tmp-{os.getpid()}"
    with bz2.BZ2File(origin, "rb") as src, open(tmp, "wb") as dst:
        dst.write(src.read())
    os.replace(tmp, path)
    return path


def set_env_var(key, val, default_val=""):
    """Set an env var, returning the previous value (parity:
    test_utils.set_env_var)."""
    import os
    prev = os.environ.get(key, default_val)
    if val is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = str(val)
    return prev


def retry(n):
    """Decorator: re-run a flaky test up to n times on assertion failure
    (parity: test_utils.retry)."""
    assert n > 0

    def decorate(f):
        import functools

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
            return None
        return wrapper
    return decorate


def check_resnet_dp_equivalence(ctxs, rs=None, batch=None):
    """BN-under-SPMD equivalence harness (VERDICT r4 #4), shared by
    tests/test_parallel.py and __graft_entry__._dryrun_resnet_dp so the
    driver dryrun and the CI test cannot drift.

    Builds a tiny-image ResNet-18 (real BatchNorm in every block) +
    SoftmaxOutput Module with KVStore('tpu_sync') and the fused
    multi-precision momentum optimizer, runs ONE forward_backward on the
    `ctxs` mesh and on a single device from identical init, and asserts
    grads and BN running stats agree tightly: under the SPMD executor
    the batch mean/var are computed over the GLOBAL batch, so a
    per-shard-statistics bug shows up as O(0.1) error while legitimate
    all-reduce summation-order noise is ~1e-4.
    (Reference harness: tests/nightly/dist_device_sync_kvstore.py:33-60.)

    Returns (build, X, Y): the module factory + dataset, so callers can
    run their own training-level checks on top (e.g. a multi-epoch fit).
    """
    from . import context as _ctx_mod  # noqa: F401  (mx.* below)
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision

    rs = rs or _np.random.RandomState(3)
    n = len(ctxs) if isinstance(ctxs, (list, tuple)) else 1
    B = batch or 2 * n
    X = rs.normal(0, 1, (2 * B, 3, 8, 8)).astype(_np.float32)
    Y = rs.randint(0, 4, 2 * B).astype(_np.float32)
    X[:, :, :4, :4] += (Y - 1.5)[:, None, None, None]  # learnable signal

    def build(cs):
        net = vision.resnet18_v1(classes=4, thumbnail=True,
                                 prefix="rn_")  # stable names across builds
        out = mx.sym.SoftmaxOutput(net(mx.sym.Variable("data")),
                                   name="softmax")
        it = mx.io.NDArrayIter(X, Y, batch_size=B)
        mod = mx.mod.Module(out, context=cs)
        mod.bind(data_shapes=it.provide_data,
                 label_shapes=it.provide_label)
        mx.random.seed(11)  # identical init across builds
        mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2))
        mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "momentum": 0.9, "wd": 1e-4,
                                             "multi_precision": True})
        return mod, it

    def one_step(cs):
        mod, it = build(cs)
        it.reset()
        mod.forward_backward(next(iter(it)))
        grads = {k: v.asnumpy() for k, v in mod._exec.grad_dict.items()}
        _, aux = mod.get_params()
        return grads, {k: v.asnumpy() for k, v in aux.items()}

    g_mesh, x_mesh = one_step(ctxs)
    g_one, x_one = one_step(ctxs[0] if isinstance(ctxs, (list, tuple))
                            else ctxs)
    assert set(g_mesh) == set(g_one) and set(x_mesh) == set(x_one)
    for k in g_mesh:
        _np.testing.assert_allclose(g_mesh[k], g_one[k],
                                    rtol=1e-2, atol=2e-3, err_msg=k)
    for k in x_mesh:  # global-batch BN stats, not shard stats
        _np.testing.assert_allclose(x_mesh[k], x_one[k],
                                    rtol=1e-3, atol=1e-4, err_msg=k)
    return build, X, Y
