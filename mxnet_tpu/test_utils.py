"""Test utilities (parity: python/mxnet/test_utils.py, 1,571 LoC).

The reference's op-test machinery: assert_almost_equal, finite-difference
check_numeric_gradient (:789), check_symbolic_forward/backward (:921,995),
rand_ndarray, default_context, and check_consistency (:1203) — re-targeted
as CPU-vs-TPU (instead of CPU-vs-GPU) cross-backend equivalence.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .context import Context, cpu, current_context
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol as sym
from . import random as _random

_rng = _np.random.RandomState(1234)


def default_context() -> Context:
    return current_context()


def set_default_context(ctx: Context) -> None:
    Context.default_ctx = ctx


def default_dtype():
    return _np.float32


def get_atol(atol=None):
    return 1e-20 if atol is None else atol


def get_rtol(rtol=None):
    return 1e-5 if rtol is None else rtol


def random_arrays(*shapes):
    arrays = [_np.array(_np.random.randn(), dtype=default_dtype())
              if len(s) == 0 else
              _np.random.randn(*s).astype(default_dtype()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def random_sample(population, k):
    population_copy = population[:]
    _np.random.shuffle(population_copy)
    return population_copy[0:k]


def rand_shape_2d(dim0=10, dim1=10):
    return _rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1)


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_rng.randint(1, dim0 + 1), _rng.randint(1, dim1 + 1),
            _rng.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 distribution=None):
    """Parity: test_utils.rand_ndarray incl. sparse storage types."""
    if stype == "default":
        return nd.array(random_arrays(shape), dtype=dtype)
    density = 0.1 if density is None else density
    dense = _np.random.randn(*shape).astype(dtype or "float32")
    mask = _np.random.rand(*shape) < density
    dense = dense * mask
    from .ndarray import sparse
    if stype == "row_sparse":
        return sparse.row_sparse_array(dense)
    if stype == "csr":
        return sparse.csr_matrix(dense)
    raise MXNetError(f"unknown storage type {stype}")


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def find_max_violation(a, b, rtol=None, atol=None):
    rtol, atol = get_rtol(rtol), get_atol(atol)
    diff = _np.abs(a - b)
    tol = atol + rtol * _np.abs(b)
    violation = diff / (tol + 1e-20)
    loc = _np.argmax(violation)
    idx = _np.unravel_index(loc, violation.shape)
    return idx, _np.max(violation)


def same(a, b):
    return _np.array_equal(a, b)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Parity: test_utils.assert_almost_equal (:467)."""
    a = a.asnumpy() if isinstance(a, NDArray) else _np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else _np.asarray(b)
    rtol, atol = get_rtol(rtol), get_atol(atol)
    if _np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    index, rel = find_max_violation(a, b, rtol, atol)
    raise AssertionError(
        f"Error {rel} exceeds tolerance rtol={rtol}, atol={atol}. "
        f"Location of maximum error: {index}, "
        f"{names[0]}={a[index]:.8f}, {names[1]}={b[index]:.8f}")


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    return _np.allclose(a, b, rtol=get_rtol(rtol), atol=get_atol(atol),
                        equal_nan=equal_nan)


def assert_exception(f, exception_type, *args, **kwargs):
    try:
        f(*args, **kwargs)
        assert False
    except exception_type:
        return


def simple_forward(sym_, ctx=None, is_train=False, **inputs):
    ctx = ctx or default_context()
    inputs = {k: nd.array(v) for k, v in inputs.items()}
    exe = sym_.bind(ctx, args=inputs)
    exe.forward(is_train=is_train)
    outputs = [o.asnumpy() for o in exe.outputs]
    if len(outputs) == 1:
        outputs = outputs[0]
    return outputs


def _parse_location(sym_, location, ctx, dtype=None):
    assert isinstance(location, (dict, list, tuple))
    if isinstance(location, dict):
        if set(location.keys()) != set(sym_.list_arguments()):
            raise ValueError(
                f"Symbol arguments and keys of the given location do not "
                f"match. symbol args: {sym_.list_arguments()}, location.keys():"
                f" {list(location.keys())}")
    else:
        location = {k: v for k, v in zip(sym_.list_arguments(), location)}
    location = {k: nd.array(v, ctx=ctx, dtype=v.dtype if dtype is None
                            else dtype)
                if isinstance(v, _np.ndarray) else
                (v if isinstance(v, NDArray) else nd.array(v, ctx=ctx))
                for k, v in location.items()}
    return location


def _parse_aux_states(sym_, aux_states, ctx, dtype=None):
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        if set(aux_states.keys()) != set(sym_.list_auxiliary_states()):
            raise ValueError("Symbol aux_states names and given aux_states "
                             "do not match.")
    elif isinstance(aux_states, (list, tuple)):
        aux_names = sym_.list_auxiliary_states()
        aux_states = {k: v for k, v in zip(aux_names, aux_states)}
    return {k: nd.array(v, ctx=ctx) if not isinstance(v, NDArray) else v
            for k, v in aux_states.items()}


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Finite-difference gradients via central differences."""
    approx_grads = {k: _np.zeros(v.shape, dtype=_np.float32)
                    for k, v in location.items()}
    for k, v in location.items():
        executor.arg_dict[k][:] = v
    for k in location:
        old_value = location[k].copy()
        for i in range(int(_np.prod(old_value.shape))):
            idx = _np.unravel_index(i, old_value.shape)
            # forward perturbed +eps
            loc_p = old_value.copy()
            loc_p[idx] += eps
            executor.arg_dict[k][:] = loc_p
            f_peps = executor.forward(is_train=use_forward_train)[0].asnumpy().sum()
            loc_m = old_value.copy()
            loc_m[idx] -= eps
            executor.arg_dict[k][:] = loc_m
            f_meps = executor.forward(is_train=use_forward_train)[0].asnumpy().sum()
            approx_grads[k][idx] = (f_peps - f_meps) / (2 * eps)
        executor.arg_dict[k][:] = old_value
    return approx_grads


def check_numeric_gradient(sym_, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, use_forward_train=True, ctx=None,
                           grad_stype_dict=None, dtype=_np.float64):
    """Finite-difference gradient checking (parity: test_utils.py:789).

    Note: runs in float32 (TPU-native default); tolerances follow the
    reference's float32-path defaults.
    """
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx)
    location_np = {k: v.asnumpy() for k, v in location.items()}
    aux = _parse_aux_states(sym_, aux_states, ctx)

    if grad_nodes is None:
        grad_nodes = [k for k in sym_.list_arguments()]
    elif isinstance(grad_nodes, dict):
        grad_nodes = list(grad_nodes.keys())

    # random projection to scalar so we check d(proj.out)/d(arg)
    out = sym_
    proj_shape = sym_.infer_shape(
        **{k: v.shape for k, v in location_np.items()})[1][0]
    proj = _np.random.uniform(-1, 1, size=proj_shape).astype(_np.float32)

    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in sym_.list_arguments()}
    exe = sym_.bind(ctx, args=location,
                    args_grad={k: nd.zeros(location[k].shape, ctx=ctx)
                               for k in grad_nodes},
                    grad_req=grad_req, aux_states=aux)
    exe.forward(is_train=True)
    exe.backward(out_grads=[nd.array(proj, ctx=ctx)])
    symbolic_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    # numeric: perturb each entry, objective = sum(out * proj)
    fwd_exe = sym_.bind(ctx, args={k: v.copy() for k, v in location.items()},
                        aux_states={k: v.copy() for k, v in aux.items()})

    def objective():
        return float((fwd_exe.forward(
            is_train=use_forward_train)[0].asnumpy() * proj).sum())

    for name in grad_nodes:
        base = location_np[name].astype(_np.float64)
        approx = _np.zeros_like(base)
        it = _np.nditer(base, flags=["multi_index"])
        while not it.finished:
            idx = it.multi_index
            pert = base.copy()
            pert[idx] += numeric_eps
            fwd_exe.arg_dict[name][:] = pert.astype(_np.float32)
            fp = objective()
            pert[idx] -= 2 * numeric_eps
            fwd_exe.arg_dict[name][:] = pert.astype(_np.float32)
            fm = objective()
            approx[idx] = (fp - fm) / (2 * numeric_eps)
            it.iternext()
        fwd_exe.arg_dict[name][:] = base.astype(_np.float32)
        assert_almost_equal(approx, symbolic_grads[name], rtol,
                            atol if atol is not None else 1e-4,
                            (f"NUMERICAL_{name}", f"BACKWARD_{name}"))


def check_symbolic_forward(sym_, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=None):
    """Parity: test_utils.py:921."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx, dtype=dtype)
    aux = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym_.list_outputs()]
    exe = sym_.bind(ctx, args=location, aux_states=aux)
    outputs = exe.forward(is_train=False)
    for output_name, expect, output in zip(sym_.list_outputs(), expected,
                                           outputs):
        assert_almost_equal(expect, output.asnumpy(), rtol, atol or 1e-5,
                            ("EXPECTED_%s" % output_name,
                             "FORWARD_%s" % output_name),
                            equal_nan=equal_nan)
    return [o.asnumpy() for o in outputs]


def check_symbolic_backward(sym_, location, out_grads, expected, rtol=1e-5,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None, grad_stypes=None, equal_nan=False,
                            dtype=None):
    """Parity: test_utils.py:995."""
    ctx = ctx or default_context()
    location = _parse_location(sym_, location, ctx=ctx, dtype=dtype)
    aux = _parse_aux_states(sym_, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = {k: v for k, v in zip(sym_.list_arguments(), expected)}
    if isinstance(grad_req, str):
        grad_req = {k: grad_req for k in sym_.list_arguments()}
    elif isinstance(grad_req, (list, tuple)):
        grad_req = {k: v for k, v in zip(sym_.list_arguments(), grad_req)}
    args_grad = {k: nd.zeros(location[k].shape, ctx=ctx)
                 for k in expected if grad_req.get(k, "null") != "null"}
    # 'add' semantics: preload random values
    adds = {}
    for k, req in grad_req.items():
        if req == "add" and k in args_grad:
            adds[k] = _np.random.normal(
                size=location[k].shape).astype(_np.float32)
            args_grad[k][:] = adds[k]
    exe = sym_.bind(ctx, args=location, args_grad=args_grad,
                    grad_req=grad_req, aux_states=aux)
    exe.forward(is_train=True)
    if isinstance(out_grads, (tuple, list)):
        out_grads = [nd.array(v, ctx=ctx) if not isinstance(v, NDArray) else v
                     for v in out_grads]
    elif isinstance(out_grads, dict):
        out_grads = [nd.array(out_grads[k], ctx=ctx)
                     for k in sym_.list_outputs()]
    exe.backward(out_grads)
    grads = {k: v.asnumpy() for k, v in exe.grad_dict.items()}
    for name in expected:
        if grad_req.get(name, "null") == "write":
            assert_almost_equal(expected[name], grads[name], rtol,
                                atol or 1e-6,
                                (f"EXPECTED_{name}", f"BACKWARD_{name}"),
                                equal_nan=equal_nan)
        elif grad_req.get(name) == "add":
            assert_almost_equal(expected[name] + adds[name],
                                grads[name], rtol, atol or 1e-6,
                                (f"EXPECTED_{name}", f"BACKWARD_{name}"),
                                equal_nan=equal_nan)
    return grads


def check_consistency(sym_, ctx_list, scale=1.0, grad_req="write",
                      arg_params=None, aux_params=None, tol=None,
                      raise_on_err=True, ground_truth=None, equal_nan=False,
                      report=None):
    """Cross-backend equivalence (parity: test_utils.py:1203 — the reference
    compared cpu vs gpu; here cpu vs tpu/accelerator ctx lists)."""
    tol = tol or {_np.dtype(_np.float16): 1e-1, _np.dtype(_np.float32): 1e-3,
                  _np.dtype(_np.float64): 1e-5, _np.dtype(_np.uint8): 0,
                  _np.dtype(_np.int32): 0}
    if isinstance(tol, float):
        tol = {_np.dtype(d): tol for d in
               (_np.float16, _np.float32, _np.float64, _np.uint8, _np.int32)}
    assert len(ctx_list) > 1
    if isinstance(sym_, sym.Symbol):
        sym_ = [sym_] * len(ctx_list)

    output_points = []
    for s, ctx in zip(sym_, ctx_list):
        ctx_spec = dict(ctx)
        context = ctx_spec.pop("ctx")
        type_dict = ctx_spec.pop("type_dict", {})
        exe = s.simple_bind(context, grad_req=grad_req, type_dict=type_dict,
                            **ctx_spec)
        if arg_params:
            for k, v in arg_params.items():
                exe.arg_dict[k][:] = v
        else:
            if not output_points:
                for name, arr in exe.arg_dict.items():
                    arr[:] = _np.random.normal(
                        size=arr.shape, scale=scale).astype(_np.float32)
                arg_params = {k: v.asnumpy() for k, v in exe.arg_dict.items()}
            else:
                for k, v in arg_params.items():
                    exe.arg_dict[k][:] = v
        if aux_params:
            for k, v in aux_params.items():
                exe.aux_dict[k][:] = v
        exe.forward(is_train=grad_req != "null")
        output_points.append([o.asnumpy() for o in exe.outputs])

    dtypes = [o.dtype for o in output_points[0]]
    gt = ground_truth or output_points[0]
    for i, outs in enumerate(output_points[1:], 1):
        for j, (g, o) in enumerate(zip(gt, outs)):
            # kind 'f' misses ml_dtypes floats (bfloat16 is kind 'V') —
            # exactly the dtypes the TPU consistency tier audits
            if report is not None and (g.dtype.kind == "f"
                                       or "float" in g.dtype.name):
                report["max_err"] = max(
                    report.get("max_err", 0.0),
                    float(_np.max(_np.abs(_np.asarray(g, _np.float64) -
                                          _np.asarray(o, _np.float64)))))
            try:
                assert_almost_equal(g, o, rtol=tol[_np.dtype(dtypes[j])],
                                    atol=tol[_np.dtype(dtypes[j])],
                                    equal_nan=equal_nan)
            except AssertionError:
                if raise_on_err:
                    raise
    return gt


def discard_stderr(*args, **kwargs):
    import contextlib
    import io
    return contextlib.redirect_stderr(io.StringIO())


def list_gpus():
    from .context import num_gpus
    return list(range(num_gpus()))


def download(url, fname=None, dirname=None, overwrite=False):
    from .gluon.utils import download as _dl
    return _dl(url, fname or dirname, overwrite)


def get_mnist(num_train=600, num_test=100):
    """Synthetic MNIST-shaped dataset when real files are unavailable
    (zero-egress environments).  LEARNABLE: each class is a fixed smooth
    prototype image plus noise, so classifiers trained on it reach high
    accuracy and demos (adversarial examples, multi-task, fine-tuning)
    behave like they do on the real data."""
    rs = _np.random.RandomState(42)
    # smooth per-class prototypes (low-freq random fields, blurred)
    protos = rs.rand(10, 1, 32, 32).astype(_np.float32)
    k = _np.ones(5, _np.float32) / 5.0  # separable box blur
    blurred = []
    for p in protos:
        img = p[0]
        for _ in range(2):
            img = _np.stack([
                _np.convolve(row, k, mode="same") for row in img])
            img = _np.stack([
                _np.convolve(col, k, mode="same") for col in img.T]).T
        blurred.append(img[2:30, 2:30])
    protos = _np.stack(blurred)[:, None]          # (10,1,28,28)
    protos = (protos - protos.min()) / (_np.ptp(protos) + 1e-9)

    def make(n):
        y = rs.randint(0, 10, n)
        x = protos[y] + rs.normal(0, 0.25, (n, 1, 28, 28))
        return x.clip(0, 1).astype(_np.float32), y.astype(_np.float32)

    train_x, train_y = make(num_train)
    test_x, test_y = make(num_test)
    return {"train_data": train_x, "train_label": train_y,
            "test_data": test_x, "test_label": test_y}


# ---------------------------------------------------------------------------
# Golden-logit zoo fixtures (VERDICT r3 #2; parity:
# tests/python/gpu/test_forward.py — committed expected logits pin the
# model zoo against silent numeric drift).  Params and inputs are
# regenerated deterministically from fixed seeds (jax PRNG + numpy
# RandomState), so the committed .npz holds only the tiny logits block.
# ---------------------------------------------------------------------------
def golden_model_cases():
    """name -> zero-arg builder returning (net, input NDArray).  Shared by
    tools/make_golden.py (writer), tests/test_golden_forward.py (CPU
    gate) and tools/run_tpu_consistency.py (on-chip check)."""
    from . import nd as _nd
    from . import random as _random
    from . import initializer as _init
    from .gluon.model_zoo import vision as _vision
    from .gluon.model_zoo.transformer import TransformerLM as _TLM

    def _vision_case(factory, shape=(2, 3, 64, 64)):
        def build():
            _random.seed(0)
            net = factory()
            net.initialize(_init.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2))
            rs = _np.random.RandomState(42)
            x = _nd.array(rs.normal(0, 1, shape).astype(_np.float32))
            return net, x
        return build

    def _lm_case():
        def build():
            _random.seed(0)
            net = _TLM(vocab=32, dim=32, num_layers=2, num_heads=4,
                       max_len=16)
            net.initialize(_init.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2))
            rs = _np.random.RandomState(42)
            x = _nd.array(rs.randint(0, 32, (2, 16)).astype(_np.float32))
            return net, x
        return build

    return {
        "resnet18_v1": _vision_case(_vision.resnet18_v1),
        "resnet18_v2": _vision_case(_vision.resnet18_v2),
        "mobilenet0_25": _vision_case(_vision.mobilenet0_25),
        "squeezenet1_0": _vision_case(_vision.squeezenet1_0),
        # densenet's final AvgPool2D(7) assumes the 224 input contract
        "densenet121": _vision_case(_vision.densenet121,
                                    shape=(1, 3, 224, 224)),
        "transformer_lm": _lm_case(),
    }


def golden_forward(name):
    """Deterministic logits for one golden case (inference mode)."""
    net, x = golden_model_cases()[name]()
    out = net(x)
    return _np.asarray(out.asnumpy(), _np.float32)


def golden_fixture_path(name):
    import os as _os
    return _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "tests", "golden",
        f"{name}.npz")
