"""Device contexts: mx.cpu()/mx.gpu()/mx.tpu() mapped onto JAX devices.

Reference parity: `python/mxnet/context.py` (Context class, with-stack,
default ctx).  TPU-native: a Context resolves to a concrete `jax.Device`;
`mx.tpu(i)` is first-class (the BASELINE.json north star).  `mx.gpu(i)` is
accepted and maps to the i-th accelerator so reference scripts run unmodified
on TPU hosts.
"""
from __future__ import annotations

from typing import Optional

import jax

from .base import MXNetError, _ThreadLocalStack

_DEVTYPE2STR = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "tpu"}
_DEVSTR2TYPE = {v: k for k, v in _DEVTYPE2STR.items()}


class Context:
    """A device context. Comparable/hashable; usable as a with-scope."""

    _stack = _ThreadLocalStack()
    default_ctx: "Context"

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in _DEVSTR2TYPE:
            raise MXNetError(f"unknown device type {device_type}")
        self.device_type = device_type
        self.device_id = device_id

    @property
    def device_typeid(self) -> int:
        return _DEVSTR2TYPE[self.device_type]

    # -- jax mapping --------------------------------------------------------
    def jax_device(self) -> jax.Device:
        """Resolve to a concrete jax.Device.

        'tpu'/'gpu' both mean "accelerator i" — on a TPU host, mx.gpu(0) from
        a reference script lands on TPU chip 0 (no GPU in the loop).
        'cpu'/'cpu_pinned' resolve to host CPU devices.
        """
        if self.device_type in ("cpu", "cpu_pinned"):
            devs = _local("cpu") if _has_platform("cpu") else _local(None)
            return devs[min(self.device_id, len(devs) - 1)]
        accels = _accelerators()
        if not accels:
            # graceful CPU fallback, mirroring mxnet's CPU-only builds
            devs = _local(None)
            return devs[min(self.device_id, len(devs) - 1)]
        if self.device_id >= len(accels):
            raise MXNetError(
                f"{self} out of range: {len(accels)} accelerator(s) visible")
        return accels[self.device_id]

    # -- scope --------------------------------------------------------------
    def __enter__(self):
        Context._stack.push(self)
        return self

    def __exit__(self, *exc):
        Context._stack.pop()

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()


def _has_platform(name: str) -> bool:
    try:
        jax.devices(name)
        return True
    except RuntimeError:
        return False


def _local(platform):
    """Process-local devices only: under multi-process jax.distributed,
    jax.devices() lists GLOBAL devices and device 0 may live on another
    host — contexts must resolve to addressable ones (parity: each ps-lite
    worker owned its own GPUs)."""
    devs = jax.local_devices() if platform is None else [
        d for d in jax.local_devices() if d.platform == platform]
    return devs if devs else (jax.devices() if platform is None
                              else jax.devices(platform))


def _accelerators():
    for plat in ("tpu", "gpu", "cuda", "rocm"):
        if _has_platform(plat):
            return _local(plat)
    return []


Context.default_ctx = Context("cpu", 0)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """First-class TPU context (north star: BASELINE.json)."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of visible accelerators (parity: mx.context.num_gpus)."""
    return len(_accelerators())


def num_tpus() -> int:
    return len(_accelerators())


def current_context() -> Context:
    return Context._stack.top() or Context.default_ctx
