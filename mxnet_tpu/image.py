"""Image IO + augmentation (parity: python/mxnet/image/image.py + the C++
augmenters in src/io/image_aug_default.cc).

Pure-python host-side pipeline: decode (cv2/PIL, gated), resize, crop,
mirror, color jitter; `ImageIter`/`ImageRecordIterPy` feed NCHW float
batches.  Heavy decode runs in the prefetch thread (io.PrefetchingIter).
"""
from __future__ import annotations

import os
import random as _pyrandom
from typing import List, Optional

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import io as _io
from . import recordio


def _as_np(src) -> _np.ndarray:
    return src.asnumpy() if isinstance(src, NDArray) else _np.asarray(src)


def _like(arr: _np.ndarray, src):
    """Wrap the numpy result to match the input's type.  The augmenter
    cores are numpy-native (the host decode pipeline must never pay a
    per-image jax dispatch — that is a ~7x throughput loss measured on
    the IO bench); NDArray in → NDArray out keeps API parity."""
    return nd.array(arr) if isinstance(src, NDArray) else arr


def imdecode_np(buf, flag=1, to_rgb=True) -> _np.ndarray:
    """Decode image bytes → HWC uint8 numpy (the iterator hot path)."""
    img = recordio._imdecode_bytes(bytes(buf), flag)
    if img is None:
        raise MXNetError("image decode failed")
    if to_rgb and img.ndim == 3:
        img = _np.ascontiguousarray(img[:, :, ::-1])
    return img


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode image bytes → HWC NDArray (parity: mx.image.imdecode)."""
    return nd.array(imdecode_np(buf, flag, to_rgb))


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def _resize_np(src: _np.ndarray, w, h):
    try:
        import cv2
        return cv2.resize(src, (w, h), interpolation=cv2.INTER_LINEAR)
    except ImportError:
        pass
    # jax bilinear fallback
    import jax
    out = jax.image.resize(src.astype(_np.float32),
                           (h, w) + src.shape[2:], method="bilinear")
    return _np.asarray(out).astype(src.dtype)


def imresize(src, w, h, interp=1):
    return _like(_resize_np(_as_np(src), w, h), src)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size` (parity: image.resize_short)."""
    arr = _as_np(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return _like(_resize_np(arr, new_w, new_h), src)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = _as_np(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[0], size[1])
    return _like(out, src)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = _pyrandom.randint(0, max(0, w - new_w))
    y0 = _pyrandom.randint(0, max(0, h - new_h))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size)
    return out, (x0, y0, new_w, new_h)


def scale_down(src_size, size):
    """Shrink a requested crop (w, h) to fit inside src (w, h) keeping
    its aspect ratio (parity: image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area-and-aspect crop resized to `size` (parity:
    image.random_size_crop — the inception-style crop).  Falls back to a
    random fitting crop when no sample satisfies the constraints."""
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = _pyrandom.uniform(min_area, 1.0) * area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round((target_area * new_ratio) ** 0.5))
        new_h = int(round((target_area / new_ratio) ** 0.5))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return random_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = _as_np(src).astype(_np.float32, copy=False)
    if mean is not None:
        arr = arr - _as_np(mean).astype(_np.float32)
    if std is not None:
        arr = arr * (1.0 / _as_np(std).astype(_np.float32))
    return _like(arr, src)


class Augmenter:
    """Base augmenter (parity: image.Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        # numpy values (mean/std arrays) serialize via tolist/str fallback
        return json.dumps([self.__class__.__name__.lower(), self._kwargs],
                          default=lambda o: o.tolist()
                          if hasattr(o, "tolist") else str(o))

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return [resize_short(src, self.size)]


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return [imresize(src, self.size[0], self.size[1])]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return [random_crop(src, self.size)[0]]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size

    def __call__(self, src):
        return [center_crop(src, self.size)[0]]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return [_like(_np.ascontiguousarray(_as_np(src)[:, ::-1]), src)]
        return [src]


class CastAug(Augmenter):
    def __call__(self, src):
        return [src.astype(_np.float32)]  # np and NDArray both


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return [src * alpha]


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = _as_np(src).astype(_np.float32, copy=False)
        coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)
        gray = float((arr * coef).sum() * (3.0 / arr.size))
        return [_like(arr * alpha + gray * (1.0 - alpha), src)]


class SaturationJitterAug(Augmenter):
    """Parity: image.py SaturationJitterAug — blend with per-pixel gray."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = _as_np(src).astype(_np.float32, copy=False)
        coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)
        gray = (arr * coef).sum(axis=2, keepdims=True)
        return [_like(arr * alpha + gray * (1.0 - alpha), src)]


class ColorJitterAug(Augmenter):
    """Parity: image.py ColorJitterAug — random-order brightness/contrast/
    saturation jitter."""

    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.ts = []
        if brightness > 0:
            self.ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.ts.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        order = list(range(len(self.ts)))
        _pyrandom.shuffle(order)
        for i in order:
            src = self.ts[i](src)[0]
        return [src]


class RandomGrayAug(Augmenter):
    """Parity: image.py RandomGrayAug — convert to 3-channel gray w.p. p."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            arr = _as_np(src).astype(_np.float32, copy=False)
            coef = _np.array([[[0.299, 0.587, 0.114]]], _np.float32)
            gray = (arr * coef).sum(axis=2, keepdims=True)
            src = _like(_np.repeat(gray, 3, axis=2), src)
        return [src]


class HueJitterAug(Augmenter):
    """Parity: image.py HueJitterAug — rotate chroma in YIQ space by a
    random angle in [-hue, hue]·π."""

    _TYIQ = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], _np.float32)
    _ITYIQ = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        rot = _np.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w],
                         [0.0, w, u]], _np.float32)
        t = (self._ITYIQ @ rot @ self._TYIQ).T
        arr = _as_np(src).astype(_np.float32, copy=False)
        return [_like(arr @ t, src)]


class LightingAug(Augmenter):
    """Parity: image.py LightingAug — AlexNet-style PCA lighting noise:
    add eigvec·(alpha∘eigval) with alpha ~ N(0, alphastd)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = self.eigvec @ (alpha * self.eigval)
        arr = _as_np(src).astype(_np.float32, copy=False)
        return [_like(arr + rgb.astype(_np.float32), src)]


class SequentialAug(Augmenter):
    """Parity: image.py SequentialAug — apply sub-augmenters in order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        imgs = [src]
        for aug in self.ts:
            imgs = [out for img in imgs for out in aug(img)]
        return imgs

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [a.dumps() for a in self.ts]]


class RandomOrderAug(Augmenter):
    """Parity: image.py RandomOrderAug — apply sub-augmenters in a
    random order."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        order = list(self.ts)
        _pyrandom.shuffle(order)
        imgs = [src]
        for aug in order:
            imgs = [out for img in imgs for out in aug(img)]
        return imgs

    def dumps(self):
        return [self.__class__.__name__.lower(),
                [a.dumps() for a in self.ts]]


class RandomSizedCropAug(Augmenter):
    """Parity: image.py RandomSizedCropAug — random_size_crop as an
    augmenter (inception training crop)."""

    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return [random_size_crop(src, self.size, self.min_area,
                                 self.ratio, self.interp)[0]]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        # numpy-native; the reciprocal turns the per-image divide into a
        # multiply on the hot path
        self.mean = None if mean is None \
            else _np.asarray(_as_np(mean), _np.float32)
        self._inv_std = None if std is None \
            else (1.0 / _np.asarray(_as_np(std), _np.float32))

    def __call__(self, src):
        arr = _as_np(src).astype(_np.float32, copy=False)
        if self.mean is not None:
            arr = arr - self.mean
        if self._inv_std is not None:
            arr = arr * self._inv_std
        return [_like(arr, src)]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Parity: image.CreateAugmenter (full flag set: rand_resize →
    inception crop, color jitters composed in random order, PCA
    lighting, random gray)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08,
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    jitters: List[Augmenter] = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if len(jitters) > 1:
        auglist.append(RandomOrderAug(jitters))
    else:
        auglist.extend(jitters)
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Pure-python image iterator (parity: python/mxnet/image/image.py
    ImageIter): reads .rec or .lst+images, applies augmenters, yields NCHW."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", preprocess_threads=4, **kwargs):
        super().__init__(batch_size)
        # decode+augment worker pool (parity: iter_image_recordio_2.cc
        # OMP-parallel decode, :139-154): cv2 releases the GIL, so a thread
        # pool gives real decode parallelism at ImageNet rates
        self._n_workers = max(1, int(preprocess_threads))
        self._pool = None
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(path_imgidx,
                                                         path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None
        self.imglist = None
        self._rec_offsets = None
        self.path_root = path_root
        if path_imglist:
            imglist_d = {}
            imgkeys = []
            with open(path_imglist) as fin:
                for line in fin:
                    line = line.strip().split("\t")
                    label = _np.array(line[1:-1], dtype=_np.float32)
                    key = int(line[0])
                    imglist_d[key] = (label, line[-1])
                    imgkeys.append(key)
            self.imglist = imglist_d
            self.seq = imgkeys
        elif isinstance(imglist, list):
            imglist_d = {}
            imgkeys = []
            for i, img in enumerate(imglist):
                key = str(i)
                label = _np.array(img[0], dtype=_np.float32) \
                    if not isinstance(img[0], _np.ndarray) else img[0]
                imglist_d[key] = (label, img[1])
                imgkeys.append(key)
            self.imglist = imglist_d
            self.seq = imgkeys
        elif self.imgidx is not None:
            self.seq = self.imgidx
        elif shuffle and self.imgrec is not None:
            # no index file: scan the .rec once for record offsets so
            # shuffle is real (the reference asserts path_imgidx instead;
            # seekable python records make the index unnecessary)
            self._rec_offsets = []
            while True:
                pos = self.imgrec.tell()
                if self.imgrec.read() is None:
                    break
                self._rec_offsets.append(pos)
            self.imgrec.reset()
            self.seq = list(range(len(self._rec_offsets)))
        else:
            self.seq = None
        assert len(data_shape) == 3 and data_shape[0] == 3 or data_shape[0] == 1
        self.provide_data = [_io.DataDesc(data_name,
                                          (batch_size,) + tuple(data_shape))]
        if label_width > 1:
            self.provide_label = [_io.DataDesc(label_name,
                                               (batch_size, label_width))]
        else:
            self.provide_label = [_io.DataDesc(label_name, (batch_size,))]
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.num_parts = num_parts
        self.part_index = part_index
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            _np.random.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                if self._rec_offsets is not None:
                    self.imgrec.seek(self._rec_offsets[idx])
                    s = self.imgrec.read()
                else:
                    s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root or "", fname), "rb") as f:
                img = f.read()
            return label, img
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _decode_augment(self, s):
        # numpy end to end: decode and every augmenter stay on the host;
        # the only device transfer is the one per-batch nd.array in
        # next() (parity goal: iter_image_recordio_2.cc keeps decode on
        # the CPU pool and hands the executor one batch tensor).  The
        # HWC→CHW transpose happens HERE so it rides the worker pool
        # instead of serializing on the batch-assembly thread.
        data = imdecode_np(s)
        for aug in self.auglist:
            data = aug(data)[0]
        arr = _as_np(data)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        c, h, w = self.data_shape
        return _np.ascontiguousarray(
            arr[:h, :w, :c].transpose(2, 0, 1), dtype=_np.float32)

    def _decode_geometric_u8(self, s):
        """device_augment host leg: decode + GEOMETRIC augmenters only
        (resize/crop); returns contiguous uint8 HWC.  The float work
        (mirror select, cast, mean/std, HWC->CHW) runs as ONE fused XLA
        program per batch (`_dev_aug_fn`), so the host pays JPEG decode
        only and the device upload is uint8 — 4x less PCIe/tunnel bytes
        than the float32 host path."""
        data = imdecode_np(s)
        for aug in self.auglist:
            if isinstance(aug, (ResizeAug, RandomCropAug, CenterCropAug,
                                ForceResizeAug)):
                data = aug(data)[0]
        arr = _as_np(data)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        c, h, w = self.data_shape
        return _np.ascontiguousarray(arr[:h, :w, :c], dtype=_np.uint8)

    @property
    def _dev_aug_fn(self):
        if getattr(self, "_dev_aug_cached", None) is None:
            import jax
            import jax.numpy as jnp
            mean = inv_std = None
            mirror = False
            for aug in self.auglist:
                if isinstance(aug, ColorNormalizeAug):
                    mean = (None if aug.mean is None
                            else jnp.asarray(aug.mean))
                    inv_std = (None if aug._inv_std is None
                               else jnp.asarray(aug._inv_std))
                elif isinstance(aug, HorizontalFlipAug):
                    mirror = True
            out_dtype = jnp.dtype(getattr(self, "_device_dtype",
                                          "float32"))

            def fn(x_u8, flips):
                x = x_u8.astype(jnp.float32)          # (B,H,W,C)
                if mirror:
                    x = jnp.where(flips[:, None, None, None],
                                  x[:, :, ::-1, :], x)
                if mean is not None:
                    x = x - mean
                if inv_std is not None:
                    x = x * inv_std
                return x.transpose(0, 3, 1, 2).astype(out_dtype)

            self._dev_aug_cached = (jax.jit(fn), mirror)
        return self._dev_aug_cached

    def _map_pool(self, fn, items):
        """Decode/augment a batch on the worker pool (order-preserving)."""
        if self._n_workers <= 1 or len(items) <= 1:
            return [fn(x) for x in items]
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(max_workers=self._n_workers)
        return list(self._pool.map(fn, items))

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_label = _np.zeros((batch_size,) + (
            (self.label_width,) if self.label_width > 1 else ()),
            dtype=_np.float32)
        samples = []
        while len(samples) < batch_size:
            samples.append(self.next_sample())
        if getattr(self, "_device_augment", False):
            # uint8 NHWC host batch -> one fused on-device program
            batch_u8 = _np.empty((batch_size, h, w, c), dtype=_np.uint8)
            arrs = self._map_pool(self._decode_geometric_u8,
                                  [s for _, s in samples])
            for i, (arr, (label, _)) in enumerate(zip(arrs, samples)):
                batch_u8[i] = arr
                batch_label[i] = label if _np.ndim(label) else float(label)
            fn, mirror = self._dev_aug_fn
            flips = (_np.random.rand(batch_size) < 0.5) if mirror \
                else _np.zeros(batch_size, bool)
            data_nd = NDArray(fn(batch_u8, flips))
            return _io.DataBatch([data_nd], [nd.array(batch_label)], 0,
                                 provide_data=self.provide_data,
                                 provide_label=self.provide_label)
        # workers hand back contiguous CHW float32; assembly is one
        # contiguous memcpy per image + one device upload per batch
        batch_data = _np.empty((batch_size, c, h, w), dtype=_np.float32)
        arrs = self._map_pool(self._decode_augment, [s for _, s in samples])
        for i, (arr, (label, _)) in enumerate(zip(arrs, samples)):
            batch_data[i] = arr
            batch_label[i] = label if _np.ndim(label) else float(label)
        i = batch_size  # full batch assembled (pad = batch_size - i = 0)
        return _io.DataBatch([nd.array(batch_data)], [nd.array(batch_label)],
                             batch_size - i,
                             provide_data=self.provide_data,
                             provide_label=self.provide_label)


class ImageRecordIterPy(ImageIter):
    """Backend for io.ImageRecordIter (parity: iter_image_recordio_2.cc)."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, mean=(0, 0, 0), std=(1, 1, 1), rand_crop=False,
                 rand_mirror=False, **kwargs):
        mean_arr = _np.array(mean) if any(mean) else None
        std_arr = _np.array(std) if any(s != 1 for s in std) else None
        aug = CreateAugmenter(data_shape, rand_crop=rand_crop,
                              rand_mirror=rand_mirror, mean=mean_arr,
                              std=std_arr)
        super().__init__(batch_size, data_shape, label_width,
                         path_imgrec=path_imgrec, shuffle=shuffle,
                         aug_list=aug, **kwargs)


# -- detection pipeline (parity: python/mxnet/image/detection.py namespace:
# mx.image.ImageDetIter / CreateDetAugmenter / Det*Aug) --------------------
from .detection import (DetAugmenter, DetBorrowAug, DetRandomSelectAug,  # noqa: E402,F401
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateDetAugmenter, ImageDetIter)
