"""ctypes bindings for the native host runtime (src/runtime/).

Loads libmxtpu_runtime.so, building it with `make native` on first import
if g++ is available; every consumer (engine, recordio, io) degrades to the
pure-python path when `lib()` returns None, so the package works without a
toolchain.
"""
from __future__ import annotations

import atexit
import ctypes
import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(os.path.dirname(_HERE))
_LIB_PATH = os.path.join(_HERE, "libmxtpu_runtime.so")

_lib = None
_tried = False


def _build():
    mk = os.path.join(_ROOT, "Makefile")
    if not os.path.exists(mk):
        return False
    try:
        subprocess.run(["make", "-C", _ROOT, "native"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _bind(l):
    u64, i32, vp, cp = (ctypes.c_uint64, ctypes.c_int, ctypes.c_void_p,
                        ctypes.c_char_p)
    l.MXTStorageAlloc.restype = vp
    l.MXTStorageAlloc.argtypes = [ctypes.c_size_t]
    l.MXTStorageFree.argtypes = [vp, ctypes.c_size_t]
    l.MXTStoragePoolStats.argtypes = [ctypes.POINTER(u64)] * 4
    l.MXTEngineStart.argtypes = [i32]
    l.MXTEngineNewVar.restype = u64
    l.MXTEngineDeleteVar.argtypes = [u64]
    l.MXTEnginePushAsync.argtypes = [
        ctypes.CFUNCTYPE(None, vp), vp,
        ctypes.POINTER(u64), i32, ctypes.POINTER(u64), i32, i32]
    l.MXTEngineWaitForVar.argtypes = [u64]
    l.MXTEngineNumWorkers.restype = i32
    l.MXTEngineNumPushed.restype = u64
    l.MXTRecordIOWriterCreate.restype = vp
    l.MXTRecordIOWriterCreate.argtypes = [cp]
    l.MXTRecordIOWriterWrite.argtypes = [vp, ctypes.c_char_p, u64]
    l.MXTRecordIOWriterWrite.restype = i32
    l.MXTRecordIOWriterTell.restype = u64
    l.MXTRecordIOWriterTell.argtypes = [vp]
    l.MXTRecordIOWriterClose.argtypes = [vp]
    l.MXTRecordIOReaderCreate.restype = vp
    l.MXTRecordIOReaderCreate.argtypes = [cp]
    l.MXTRecordIOReaderNext.argtypes = [vp, ctypes.POINTER(vp),
                                        ctypes.POINTER(u64)]
    l.MXTRecordIOReaderNext.restype = i32
    l.MXTRecordIOReaderSeek.argtypes = [vp, u64]
    l.MXTRecordIOReaderTell.restype = u64
    l.MXTRecordIOReaderTell.argtypes = [vp]
    l.MXTRecordIOReaderClose.argtypes = [vp]
    l.MXTBatchLoaderCreate.restype = vp
    l.MXTBatchLoaderCreate.argtypes = [cp, i32, u64, i32, i32, i32, u64]
    l.MXTBatchLoaderNext.argtypes = [vp, ctypes.POINTER(vp),
                                     ctypes.POINTER(vp)]
    l.MXTBatchLoaderNext.restype = i32
    l.MXTBatchLoaderReset.argtypes = [vp]
    l.MXTBatchLoaderNumSamples.restype = u64
    l.MXTBatchLoaderNumSamples.argtypes = [vp]
    l.MXTBatchLoaderFree.argtypes = [vp]
    l.MXTGetLastError.restype = cp
    return l


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if os.environ.get("MXNET_TPU_NO_NATIVE"):
        return None
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        _lib = _bind(ctypes.CDLL(_LIB_PATH))
    except OSError:
        _lib = None
    if _lib is not None:
        # drain queued host-engine ops BEFORE interpreter finalization: the
        # C++ static destructor would otherwise run ctypes trampolines on a
        # dead interpreter
        atexit.register(_lib.MXTEngineWaitAll)
    return _lib


def lib_if_loaded():
    """The native library only if already loaded — never triggers a build.
    Use from sync primitives (waitall) that must not stall on `make`."""
    return _lib
