"""Profile-guided autotuning: measured knobs + scan-compiled supersteps.

Two halves, one package:

- ``superstep``: ``SuperStepCompiler`` extends the whole-step compiler
  (gluon/wholestep.py) by ``lax.scan``ning its donated step program
  over K host-prefetched batches — K training steps become ONE XLA
  dispatch, with params/opt-state/compression-residuals/loss-scaler
  threaded as the (still donated) scan carry and the K losses stacked
  for per-step visibility.
- ``sweep`` + ``decisions``: a measured tuner (paired-interleave
  probes, PR 13's bench statistic as a library) that picks superstep K
  against HBM headroom, ``MXNET_BUCKET_SIZE_MB``, serving bucket
  lattices, and the MicroBatcher hold window per (model-signature,
  platform), persisting decisions atomically next to the compile cache.
  Everything gates on ``MXNET_AUTOTUNE`` and every knob stays
  overridable by its existing env var.

Submodule imports are lazy so ``decisions`` consumers (trainer,
serving) don't drag jax-heavy sweep machinery in at import time.
"""
from __future__ import annotations

from . import decisions  # noqa: F401 — lightweight (no jax at import)

__all__ = ["SuperStepCompiler", "decisions", "sweep", "tune"]


def __getattr__(name):
    # importlib.import_module, NOT `from . import x`: the from-import
    # re-enters this __getattr__ via hasattr() before the submodule
    # binds, recursing forever
    import importlib
    if name in ("superstep", "sweep"):
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "SuperStepCompiler":
        return importlib.import_module(
            ".superstep", __name__).SuperStepCompiler
    if name == "tune":
        return importlib.import_module(".sweep", __name__).tune
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
