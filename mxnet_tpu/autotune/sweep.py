"""Measured-sweep tuner: short paired-interleave probes pick the knobs.

PR 13's bench methodology — alternate the two legs pair-by-pair,
median the adjacent-pair deltas, take the best third-sized chunk so a
noisy-neighbor burst on a shared container cannot fake a regression —
packaged as a LIBRARY (the bench riders and this tuner share the same
statistic, so a tuned decision and a bench verdict can never disagree
on methodology).

``tune()`` is the entry point: it sweeps superstep K (against the HBM
ledger's headroom — staging K batches asks ``ensure_headroom`` first),
measures the bucketed flatten/reduce across ``MXNET_BUCKET_SIZE_MB``
candidates, derives a serving bucket lattice from observed shape
traffic and a ``MicroBatcher`` hold window from the dispatch EWMA, and
persists the result via ``autotune/decisions.py`` — paid once per
(model-signature, platform), reloaded with zero re-sweep afterwards.
Every knob stays overridable by its env var (``decisions.KNOB_ENV``).
"""
from __future__ import annotations

import logging
import os
import time
from statistics import median
from typing import Dict, List, Optional, Sequence

import numpy as _np

from ..base import getenv
from . import decisions as _decisions

logger = logging.getLogger("mxnet_tpu.autotune.sweep")

#: measured probe invocations performed by the LAST tune() call — the
#: autotune-smoke gate asserts this is 0 on a decision-cache hit
last_sweep_runs: int = 0


# -- the PR 13 statistic, as a library ---------------------------------------
def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def chunked_delta_pct(deltas: Sequence[float], ref_s: float) -> float:
    """The paired-interleave estimator: median of adjacent-pair deltas
    over third-sized chunks, best chunk wins — a transient load burst
    poisons at most one chunk, not the verdict.  Returns the delta as a
    percentage of ``ref_s`` (negative = the "on" leg is faster)."""
    if not deltas or ref_s <= 0:
        return 0.0
    third = max(1, len(deltas) // 3)
    cands = [median(deltas[i:i + third])
             for i in range(0, len(deltas) - third + 1, third)]
    return min(cands) / ref_s * 100.0


def paired_interleave(fn_on, fn_off, pairs: int = 12,
                      warmup: int = 2) -> Dict[str, float]:
    """Interleaved A/B timing of two thunks (each must block until its
    work is DONE — include the device sync).  Pair order alternates per
    iteration so drift cancels; returns median leg times and the
    chunked delta percentage of on-vs-off."""
    global last_sweep_runs
    for _ in range(warmup):
        fn_on()
        fn_off()
    on_times: List[float] = []
    off_times: List[float] = []
    deltas: List[float] = []
    for i in range(pairs):
        if i % 2 == 0:
            t_on = _timed(fn_on)
            t_off = _timed(fn_off)
        else:
            t_off = _timed(fn_off)
            t_on = _timed(fn_on)
        on_times.append(t_on)
        off_times.append(t_off)
        deltas.append(t_on - t_off)
        last_sweep_runs += 2
    off_med = median(off_times)
    return {
        "on_med_s": median(on_times),
        "off_med_s": off_med,
        "delta_pct": round(chunked_delta_pct(deltas, off_med), 3),
        "pairs": pairs,
    }


# -- knob sweeps -------------------------------------------------------------
def sweep_superstep_k(stepper, data, label,
                      ks: Sequence[int] = (2, 4, 8),
                      pairs: int = 6) -> dict:
    """Measure superstep K candidates against the K=1 whole-step
    baseline on the LIVE compiler: for each K, paired-interleave one
    ``superstep`` over K copies of the batch against K sequential
    ``step`` calls (per-step wall time both ways).  Staging asks the
    HBM ledger for headroom inside ``superstep``; a candidate that
    demoted (scan never ran) is recorded ineligible rather than scored
    on its fallback timing.  Returns ``{"best_k", "table"}``."""
    import numpy as np

    def _sync(loss):
        np.asarray(loss.asnumpy())

    table: Dict[str, dict] = {}
    best_k, best_per_step = 1, None
    for k in ks:
        datas = [data] * k
        labels = [label] * k

        def fn_super():
            _sync(stepper.superstep(datas, labels))

        def fn_seq():
            for d, l in zip(datas, labels):
                _sync(stepper.step(d, l))

        was_ran = stepper.super_active
        r = paired_interleave(fn_super, fn_seq, pairs=pairs)
        scanned = stepper.super_active or was_ran
        per_step_ms = r["on_med_s"] / k * 1e3
        base_ms = r["off_med_s"] / k * 1e3
        table[str(k)] = {
            "superstep_ms_per_step": round(per_step_ms, 4),
            "wholestep_ms_per_step": round(base_ms, 4),
            "delta_pct": r["delta_pct"],
            "scanned": bool(scanned),
        }
        if not scanned:
            continue
        if best_per_step is None or per_step_ms < best_per_step:
            best_per_step, best_k = per_step_ms, k
        if best_per_step is not None and base_ms < best_per_step:
            # the K=1 baseline beat every scanned candidate so far
            pass
    # K=1 wins when no scanned candidate improved on its own baseline
    if best_per_step is not None:
        base = min(float(t["wholestep_ms_per_step"])
                   for t in table.values())
        if base <= best_per_step:
            best_k = 1
    return {"best_k": int(best_k), "table": table}


def sweep_bucket_size(sig, candidates_mb: Sequence[float] = (8, 32, 128),
                      iters: int = 6) -> dict:
    """Measure the fused flatten+unflatten round trip of the gradient
    bucketer per ``MXNET_BUCKET_SIZE_MB`` candidate on this platform —
    the part of the step the knob actually moves on a single host.
    ``sig``: the trainer's (shape, dtype) gradient signature."""
    global last_sweep_runs
    import jax
    import jax.numpy as jnp

    from ..kvstore import GradBucketer

    grads = [jnp.ones(shape, dtype=dtype) for shape, dtype in sig]
    table: Dict[str, dict] = {}
    best_mb, best_s = None, None
    for mb in candidates_mb:
        bk = GradBucketer(sig, int(float(mb) * 1024 * 1024))

        @jax.jit
        def _roundtrip(gs, _bk=bk):
            return _bk.unflatten_inline(_bk.flatten_inline(list(gs)))

        jax.block_until_ready(_roundtrip(grads))  # compile
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(_roundtrip(grads))
            times.append(time.perf_counter() - t0)
            last_sweep_runs += 1
        med = median(times)
        table[str(mb)] = {"med_ms": round(med * 1e3, 4),
                          "buckets": len(bk.sizes)}
        if best_s is None or med < best_s:
            best_s, best_mb = med, float(mb)
    return {"best_mb": best_mb, "table": table}


# -- observation-derived serving knobs ---------------------------------------
def lattice_from_traffic(sizes: Sequence[int], max_batch: int,
                         max_rungs: int = 6) -> List[int]:
    """A serving bucket lattice from OBSERVED batch-size traffic:
    quantile rungs (p50/p75/p90/p99) rounded up to the next power of
    two — requests pad to the nearest rung above, so rungs sit just
    above where traffic actually clusters instead of a blind pow2
    ladder over the whole declared range.  Always covers ``max_batch``
    (the compile-ahead ceiling)."""
    mb = max(1, int(max_batch))
    obs = sorted(int(s) for s in sizes if 0 < int(s) <= mb)
    if not obs:
        from ..serving.buckets import pow2_buckets
        return pow2_buckets(mb)

    def _pow2_up(n: int) -> int:
        p = 1
        while p < n:
            p <<= 1
        return min(p, mb)

    rungs = {mb}
    for q in (0.50, 0.75, 0.90, 0.99):
        rungs.add(_pow2_up(obs[min(len(obs) - 1,
                                   int(q * (len(obs) - 1)))]))
    out = sorted(rungs)
    while len(out) > max_rungs:
        # drop the rung whose removal wastes the least padding: merge
        # the closest adjacent pair (keep the ceiling)
        gaps = [(out[i + 1] - out[i], i) for i in range(len(out) - 1)]
        _, i = min(gaps)
        out.pop(i)
    return out


def max_wait_from_ewma(dispatch_ewma_ms: Optional[float],
                       floor_ms: float = 0.25,
                       cap_ms: float = 5.0) -> float:
    """MicroBatcher hold window from the measured dispatch EWMA: half a
    dispatch — long enough that coalescing arrivals beats dispatching
    them separately, short enough that a lone request's added latency
    stays below the work it waits for.  Clamped to [floor, cap]."""
    if not dispatch_ewma_ms or dispatch_ewma_ms <= 0:
        return 2.0  # the documented MXNET_SERVE_MAX_WAIT_MS default
    return round(min(cap_ms, max(floor_ms, 0.5 * dispatch_ewma_ms)), 3)


# -- the tuner ---------------------------------------------------------------
def tune(net, loss_fn, trainer, data, label,
         ks: Sequence[int] = (2, 4, 8), pairs: int = 6,
         bucket_candidates_mb: Sequence[float] = (8, 32, 128),
         serve_traffic: Optional[Sequence[int]] = None,
         serve_max_batch: Optional[int] = None,
         apply_env: bool = True, force: bool = False) -> Optional[dict]:
    """Run the measured sweeps for this (model, platform) and persist
    the decision.  A persisted decision short-circuits the whole sweep
    (``last_sweep_runs == 0``) unless ``force``.  Requires
    ``MXNET_AUTOTUNE=1`` (gate) and ``MXNET_WHOLE_STEP=1`` (the
    superstep builds on the whole-step program; enabled for the sweep's
    duration if off).  ``apply_env`` exports ``MXNET_PREFETCH_DEPTH=K``
    for downstream prefetchers unless the user already pinned it.
    Returns the decision record (with ``evidence.sweep_runs``)."""
    global last_sweep_runs
    if not _decisions.ENABLED:
        logger.warning("autotune.tune() called with MXNET_AUTOTUNE "
                       "disabled — no sweep, no decision")
        return None
    last_sweep_runs = 0
    from .superstep import SuperStepCompiler

    saved_ws = os.environ.get("MXNET_WHOLE_STEP")
    if not getenv("MXNET_WHOLE_STEP", False):
        os.environ["MXNET_WHOLE_STEP"] = "1"
    try:
        stepper = net if isinstance(net, SuperStepCompiler) else \
            SuperStepCompiler(net, loss_fn, trainer)
        # warm: builds the graph (and materializes deferred shapes)
        stepper.step(data, label)
        stepper.step(data, label)
        sig = stepper.decision_signature
        if sig is None:
            logger.warning("autotune: model not whole-step compilable "
                           "(%s) — nothing to tune",
                           stepper.fallback_reason)
            return None
        rec = None if force else _decisions.load(sig)
        if rec is not None:
            logger.info("autotune: decision cache hit for %s — zero "
                        "sweep runs", sig)
            return rec
        k_sweep = sweep_superstep_k(stepper, data, label, ks=ks,
                                    pairs=pairs)
        bucket_sweep = sweep_bucket_size(stepper._built["sig"],
                                         candidates_mb=
                                         bucket_candidates_mb)
        knobs = {
            "superstep_k": k_sweep["best_k"],
            "bucket_size_mb": bucket_sweep["best_mb"],
            "prefetch_depth": max(2, k_sweep["best_k"]),
        }
        from ..observability import flight as _flight
        ewma = _flight.watch_ewma("serve_dispatch")
        knobs["serve_max_wait_ms"] = max_wait_from_ewma(
            ewma * 1e3 if ewma else None)
        if serve_traffic and serve_max_batch:
            knobs["serve_buckets"] = ",".join(
                str(b) for b in lattice_from_traffic(serve_traffic,
                                                     serve_max_batch))
        evidence = {
            "sweep_runs": last_sweep_runs,
            "superstep": k_sweep["table"],
            "bucket_size": bucket_sweep["table"],
            "serve_dispatch_ewma_ms":
                round(ewma * 1e3, 4) if ewma else None,
            "batch_shape": list(_np.shape(data.asnumpy())) if hasattr(
                data, "asnumpy") else None,
        }
        rec = {"schema": 1, "signature": sig, "knobs": knobs,
               "evidence": evidence}
        path = _decisions.store(sig, knobs, evidence)
        if path:
            rec = _decisions.load(sig)
        if apply_env and "MXNET_PREFETCH_DEPTH" not in os.environ:
            # the satellite contract: autotune stages depth>=K for the
            # prefetchers; an explicit user pin always wins
            os.environ["MXNET_PREFETCH_DEPTH"] = \
                str(knobs["prefetch_depth"])
        return rec
    finally:
        if saved_ws is None:
            os.environ.pop("MXNET_WHOLE_STEP", None)
        else:
            os.environ["MXNET_WHOLE_STEP"] = saved_ws
