"""``python -m mxnet_tpu.autotune --smoke``: the autotune CI gate.

Runs the measured tuner on a tiny pinned MLP and asserts the decision
lifecycle end to end: the sweep completes quickly, the decision file
round-trips through ``decisions.load``, and a second ``tune()`` against
the same (model-signature, platform) is a pure cache hit — ZERO
measured runs.  ``--expect-cached`` makes a cache miss fatal, so the
Makefile target can invoke the module twice and prove the
cross-process reload too.  Prints a one-line JSON verdict; exit 0/1.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _build():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(1))
    net.hybridize()
    net.initialize(mx.init.Xavier(), ctx=mx.cpu())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore="tpu_sync", update_on_kvstore=False)
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.normal(0, 1, (32, 16)).astype("f"))
    y = mx.nd.array(rs.normal(0, 1, (32, 1)).astype("f"))
    return net, gluon.loss.L2Loss(), tr, x, y


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m mxnet_tpu.autotune")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny pinned-MLP sweep + decision round-trip")
    ap.add_argument("--expect-cached", action="store_true",
                    help="fail unless the decision loads with zero "
                         "measured runs (second-process half of the "
                         "autotune-smoke gate)")
    ap.add_argument("--dir", default=None,
                    help="decision dir (default MXNET_AUTOTUNE_DIR)")
    args = ap.parse_args(argv)
    if not args.smoke:
        ap.print_help()
        return 2

    if args.dir:
        os.environ["MXNET_AUTOTUNE_DIR"] = args.dir
    # the sweep's first K-scan compile trips the flight recorder's
    # slow-sample anomaly dump — keep those artifacts in the decision
    # dir, not the invoker's cwd
    if os.environ.get("MXNET_AUTOTUNE_DIR"):
        os.environ.setdefault("MXNET_FLIGHT_DIR",
                              os.environ["MXNET_AUTOTUNE_DIR"])

    from mxnet_tpu.autotune import decisions, sweep

    decisions.enable()
    t0 = time.time()
    out = {"ok": False, "expect_cached": bool(args.expect_cached)}
    try:
        net, loss_fn, tr, x, y = _build()
        rec = sweep.tune(net, loss_fn, tr, x, y, ks=(2, 4), pairs=4,
                         bucket_candidates_mb=(8, 32), apply_env=False)
        if rec is None:
            raise RuntimeError("tune() returned no decision")
        out["sweep_runs"] = sweep.last_sweep_runs
        out["knobs"] = rec["knobs"]
        # round-trip: a fresh load (parse cache dropped) must agree
        decisions.reset_cache()
        rt = decisions.load(rec["signature"])
        if decisions.decisions_dir() is not None:
            if rt is None or rt["knobs"] != rec["knobs"]:
                raise RuntimeError(
                    f"decision round-trip mismatch: {rt!r}")
        if args.expect_cached and sweep.last_sweep_runs != 0:
            raise RuntimeError(
                f"expected a pure decision-cache hit but the tuner "
                f"performed {sweep.last_sweep_runs} measured runs")
        if not args.expect_cached and sweep.last_sweep_runs == 0:
            raise RuntimeError("first tune() performed zero measured "
                               "runs — the sweep never executed")
        out["ok"] = True
    except Exception as e:  # noqa: BLE001 — CI gate: report, don't crash
        out["error"] = f"{type(e).__name__}: {e}"
    out["elapsed_s"] = round(time.time() - t0, 2)
    print(json.dumps(out, sort_keys=True))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
