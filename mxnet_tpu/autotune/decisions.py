"""Persisted autotune decisions — measured once, reloaded forever.

The sweep tuner (``autotune/sweep.py``) is the TVM observation (arxiv
1802.04799) applied to this graft's knobs: the constants the docs tell
users to hand-tune — superstep K, ``MXNET_BUCKET_SIZE_MB``, the serving
bucket lattice, the ``MicroBatcher`` hold window — are *measurable* on
the actual (model, platform), so measure them once and persist the
answer exactly like AOT programs persist in the compile cache: paid on
the first run, reloaded with zero re-sweep afterwards.

One JSON file per (signature, platform) under ``decisions_dir()``
(``MXNET_AUTOTUNE_DIR``, else ``autotune-decisions/`` next to the
persistent compile cache — the same siting rule as the perf-regression
baselines).  Writes are crash-atomic (``base.atomic_write``).  A
signature is a content hash of what the decision depends on
(``model_signature`` for training knobs; serving knobs key on the
bucket-spec shapes), so a model change simply misses the cache and
re-tunes rather than applying a stale decision.

Precedence per knob (``KNOB_ENV``): an explicitly-set env var ALWAYS
wins — consumers check their own env first and only then consult
``knob()`` — so a user pin survives any decision file.  The whole
subsystem gates on ``MXNET_AUTOTUNE`` (default off): disabled, every
hook is one module-global boolean test.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional

from ..base import atomic_write, getenv

logger = logging.getLogger("mxnet_tpu.autotune")

#: the MXNET_AUTOTUNE kill-switch (gate-hygiene contract: off = one
#: module-global boolean test in every consumer hook)
ENABLED: bool = bool(getenv("MXNET_AUTOTUNE", False))

_SCHEMA = 1

#: knob name -> the env var that overrides it (the pre-existing manual
#: pins; an explicitly-set env always beats a persisted decision)
KNOB_ENV = {
    "superstep_k": "MXNET_SUPERSTEP_K",
    "bucket_size_mb": "MXNET_BUCKET_SIZE_MB",
    "serve_buckets": "MXNET_SERVE_BUCKETS",
    "serve_max_wait_ms": "MXNET_SERVE_MAX_WAIT_MS",
    "prefetch_depth": "MXNET_PREFETCH_DEPTH",
}

#: in-process parse cache: (signature, platform) -> record | None.
#: Decisions are immutable once written (store() repopulates), so a
#: plain dict is safe; reset_cache() drops it for tests.
_cache: Dict[tuple, Optional[dict]] = {}


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def reset_cache() -> None:
    _cache.clear()


def decisions_dir() -> Optional[str]:
    """Where decisions persist: ``MXNET_AUTOTUNE_DIR``, else an
    ``autotune-decisions/`` directory next to the persistent compile
    cache (``MXNET_COMPILE_CACHE_DIR``).  None disables persistence —
    the tuner still runs, its answer just dies with the process."""
    d = os.environ.get("MXNET_AUTOTUNE_DIR")
    if d:
        return d
    c = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    return os.path.join(c, "autotune-decisions") if c else None


def _platform() -> str:
    import jax
    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001 — a dead backend must not kill tuning
        return "unknown"


def model_signature(sig, extra=()) -> str:
    """Content hash of a parameter signature (the ``built["sig"]`` /
    ``Trainer._ensure_bucketer`` tuple of (shape, dtype) pairs) plus
    any extra decision-relevant config — the training-knob decision
    key.  A model/batch change hashes differently and misses the
    decision cache instead of inheriting a stale K."""
    return hashlib.sha1(
        repr((tuple(sig), tuple(extra))).encode()).hexdigest()[:16]


def decision_path(signature: str, platform: Optional[str] = None) \
        -> Optional[str]:
    d = decisions_dir()
    if d is None:
        return None
    return os.path.join(
        d, f"autotune-{signature}-{platform or _platform()}.json")


def load(signature: str, platform: Optional[str] = None) \
        -> Optional[dict]:
    """The persisted decision record for (signature, platform), schema-
    checked; None on miss or corruption (corrupt files warn once and
    are treated as a miss — the tuner just re-sweeps)."""
    plat = platform or _platform()
    ck = (signature, plat)
    if ck in _cache:
        return _cache[ck]
    path = decision_path(signature, plat)
    rec = None
    if path is not None and os.path.exists(path):
        try:
            with open(path) as f:
                data = json.load(f)
            if not isinstance(data, dict) or data.get("schema") != _SCHEMA \
                    or not isinstance(data.get("knobs"), dict):
                raise ValueError("missing/invalid required fields")
            rec = data
        except Exception as e:  # noqa: BLE001 — reject loudly, never crash
            logger.warning(
                "autotune: decision file %s is corrupt (%s) — ignored; "
                "the next tune() rewrites it", path, e)
    _cache[ck] = rec
    return rec


def store(signature: str, knobs: Dict[str, Any], evidence=None,
          platform: Optional[str] = None) -> Optional[str]:
    """Atomically persist a decision record; returns the path (None
    when no decisions dir is configured)."""
    plat = platform or _platform()
    path = decision_path(signature, plat)
    rec = {
        "schema": _SCHEMA,
        "signature": signature,
        "platform": plat,
        "knobs": dict(knobs),
        "evidence": dict(evidence or {}),
        "written_at": time.time(),
    }
    _cache[(signature, plat)] = rec
    if path is None:
        return None
    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_write(path, json.dumps(rec, indent=1, sort_keys=True))
    logger.info("autotune: wrote decision %s (knobs %s)", path,
                sorted(knobs))
    return path


def knob(signature: str, name: str, default=None,
         platform: Optional[str] = None):
    """The persisted value of one knob, or ``default``.  Consumers must
    check their own env var FIRST (``KNOB_ENV[name]``) — an explicit
    env pin always beats the decision file — and call this only when
    the env is unset."""
    if not ENABLED:
        return default
    rec = load(signature, platform)
    if rec is None:
        return default
    return rec["knobs"].get(name, default)
