"""Scan-compiled K-step supersteps: K training steps = ONE dispatch.

PR 10 compiled the whole training step into one donated XLA program;
the remaining per-step cost is pure host overhead — the dispatch hop
through the TPU tunnel, the supervisor/flight/goodput hooks, the python
driver loop.  The Julia-to-TPU observation (arxiv 1810.09868) is that
once the step is one program, the *loop* compiles too:
``SuperStepCompiler`` wraps ``WholeStepCompiler``'s raw step function
(``_make_ftrain`` — the exact same tracer, shared so the bitwise-parity
contract is structural) in a ``jax.lax.scan`` over K host-prefetched
batches.  Params, optimizer state, 2-bit compression residuals, the
fp16 loss scaler, BN aux state, and the applied-step counter thread
through the scan CARRY (still donated); per-step losses come back
STACKED so per-step visibility survives; the fp16 skip-step select and
scale growth/backoff run per scan iteration exactly as they do per
sequential step.

Numerics: an f32 superstep is bitwise-identical to K sequential
whole-steps on the pinned nets (tests/test_superstep.py) — same op
sequence, same RNG key stream (K keys drawn from the same
``random.next_key`` sequence), same per-step lr/wd rows (stacked
host-side, so lr schedules that move mid-superstep stay exact).

Eligibility is whole-step eligibility; anything the whole-step tracer
rejects — and a refused HBM-headroom ask for staging K batches — warns
once and falls back to K=1 whole-step (which itself falls back to the
fused path when MXNET_WHOLE_STEP is off).  K resolves as
``MXNET_SUPERSTEP_K`` > constructor arg > persisted autotune decision
(``autotune/decisions.py``) > 4.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as _np

from ..base import getenv
from ..faultinject import fire as _fi_fire
from ..ndarray import NDArray
from ..analysis import hot_path
from ..analysis import sanitizer as _san
from ..gluon.wholestep import WholeStepCompiler, _AmpIneligible, \
    _Ineligible, _ShardIneligible, amp_policy
from ..observability import flight as _flight
from ..observability import introspect as _introspect
from ..observability import journal as _journal
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from .. import autograd
from ..gluon.parameter import DeferredInitializationError
from . import decisions as _decisions

logger = logging.getLogger("mxnet_tpu.autotune.superstep")

#: default superstep length when neither env, constructor, nor a
#: persisted decision pins one
DEFAULT_K = 4


class _SuperIneligible(RuntimeError):
    """THIS call cannot run as a scanned superstep (e.g. the HBM ledger
    refused headroom for staging K batches) — demote to K=1 whole-step
    for the call without permanently demoting the compiler."""


class SuperStepCompiler(WholeStepCompiler):
    """K whole training steps as ONE scanned, donated XLA program.

    ::

        stepper = mx.autotune.SuperStepCompiler(net, loss_fn, trainer)
        K = stepper.k
        for datas, labels in staged_groups_of_K:
            losses = stepper.superstep(datas, labels)   # (K, ...) loss

    ``superstep`` accepts either a list/tuple of K per-step batches or
    pre-stacked arrays with a leading K axis (what a ``depth>=K``
    prefetcher stages); it returns the K per-step losses stacked on
    axis 0.  ``step`` (inherited) still runs single whole-steps — the
    two share program caches, hyper plumbing, and writeback, so modes
    can interleave freely.
    """

    def __init__(self, net, loss_fn, trainer, k=None):
        super().__init__(net, loss_fn, trainer)
        self._k_arg = k
        self._super_warned = False    # demotion to K=1, warn once
        self._super_ran = False       # a scan program has executed
        self._stack_cache = {}        # last-value cache: stacked lr/wd

    # -- K resolution --------------------------------------------------------
    @property
    def k(self) -> int:
        """The superstep length the training loop should stage for:
        ``MXNET_SUPERSTEP_K`` > constructor ``k`` > persisted autotune
        decision for this (model-signature, platform) > 4."""
        env_k = int(getenv("MXNET_SUPERSTEP_K", 0))
        if env_k > 0:
            return env_k
        if self._k_arg is not None:
            return max(1, int(self._k_arg))
        sig = self.decision_signature
        if sig is not None:
            dk = _decisions.knob(sig, "superstep_k", None)
            if dk is not None:
                return max(1, int(dk))
        return DEFAULT_K

    @property
    def decision_signature(self):
        """The autotune decision key for this model: a content hash of
        the trainable-parameter signature (None until the graph builds
        — resolving K before the first step falls through to the
        static default)."""
        if self._built is None:
            return None
        return _decisions.model_signature(self._built["sig"])

    @property
    def super_active(self) -> bool:
        """True once a scanned superstep program has executed."""
        return self._super_ran

    # -- public entry --------------------------------------------------------
    @hot_path
    def superstep(self, datas, labels, batch_size=None):
        """Run ``len(datas)`` training steps in one dispatch; returns
        the per-step losses stacked on axis 0 (an NDArray of shape
        ``(K, *loss_shape)`` — per-step visibility survives the fusion).

        ``datas``/``labels``: a list/tuple of K same-shaped NDArray
        batches, or ONE NDArray with a leading K axis (pre-staged)."""
        datas, labels, k, stacked = self._normalize(datas, labels)
        bs = batch_size if batch_size is not None else \
            int(datas[0].shape[0]) if not stacked else int(datas.shape[1])
        if k == 1 or self._fallback_reason is not None \
                or not getenv("MXNET_WHOLE_STEP", False):
            if k > 1:
                self._warn_demoted(
                    "MXNET_WHOLE_STEP is not enabled"
                    if self._fallback_reason is None
                    else self._fallback_reason)
            return self._sequential(datas, labels, bs, k, stacked)
        if autograd.is_recording():
            from ..base import MXNetError
            raise MXNetError(
                "SuperStepCompiler.superstep() must not be called inside "
                "autograd.record() — it manages forward/backward itself")
        policy = amp_policy()
        try:
            built = self._ensure_built()
            return self._run_super(built, datas, labels, bs, policy, k,
                                   stacked)
        except DeferredInitializationError:
            return self._sequential(datas, labels, bs, k, stacked)
        except _SuperIneligible as e:
            # per-call demotion (headroom refusal): the scan program
            # stays viable for the next call
            self._warn_demoted(str(e))
            return self._sequential(datas, labels, bs, k, stacked)
        except _AmpIneligible as e:
            self._warn_demoted(str(e))
            return self._sequential(datas, labels, bs, k, stacked)
        except _ShardIneligible as e:
            # per-call (ragged batch vs mesh data axis): K=1 whole-step
            # handles each batch, which itself falls back per step
            self._warn_demoted(str(e))
            return self._sequential(datas, labels, bs, k, stacked)
        except _Ineligible as e:
            self._warn_demoted(str(e))
            self._note_fallback(str(e))
            return self._sequential(datas, labels, bs, k, stacked)
        except Exception as e:  # noqa: BLE001 — tracing arbitrary graphs
            if self._ran or self._super_ran \
                    or self._is_execution_failure(e) \
                    or self._is_transient(e):
                # execution-typed failure: donated buffers were in play
                # — propagate for a supervisor restore+retry, exactly
                # like WholeStepCompiler.step (the superstep IS the
                # retry unit: a restore rewinds to the last superstep
                # boundary and the whole K-batch group replays)
                raise
            self._warn_demoted(f"{type(e).__name__}: {e}")
            self._note_fallback(f"{type(e).__name__}: {e}")
            return self._sequential(datas, labels, bs, k, stacked)

    # -- fallback ------------------------------------------------------------
    def _warn_demoted(self, reason: str) -> None:
        if not self._super_warned:
            logger.warning(
                "superstep demoted to K=1 whole-step (%s) — steps run "
                "one dispatch each instead of one dispatch per K",
                reason)
            self._super_warned = True

    def _slice(self, arrs, i, stacked):
        if not stacked:
            return arrs[i]
        return NDArray(arrs._data[i], arrs.context)

    def _sequential(self, datas, labels, bs, k, stacked):
        """K=1 fallback: run the batches through the inherited
        whole-step ``step`` (which itself falls back to the fused path
        when ineligible) and restack the losses."""
        losses = [self.step(self._slice(datas, i, stacked),
                            self._slice(labels, i, stacked),
                            batch_size=bs)
                  for i in range(k)]
        ctx = losses[0].context
        return NDArray(jnp.stack([l._data for l in losses]), ctx)

    @staticmethod
    def _normalize(datas, labels):
        if isinstance(datas, (list, tuple)):
            if not isinstance(labels, (list, tuple)) \
                    or len(labels) != len(datas) or not datas:
                from ..base import MXNetError
                raise MXNetError(
                    "superstep: datas and labels must be same-length "
                    "non-empty lists (or both pre-stacked NDArrays)")
            return list(datas), list(labels), len(datas), False
        # pre-stacked: leading axis is the superstep axis
        k = int(datas.shape[0])
        return datas, labels, k, True

    # -- the scanned program -------------------------------------------------
    def _build_super_fn(self, built, opt_, policy, thr, window, k):
        """``lax.scan`` the raw whole-step function over K batches.

        fsuper(gparams, states, residuals, scaler, aux, consts, datas,
               labels, keys, lrs, wds, ts)
          -> (losses[K], new_aux, new_params, new_states,
              new_residuals, new_scaler, new_ts)

        The carry is (params, opt states, residuals, scaler, aux, ts)
        — everything a sequential step would donate and write back; xs
        are the per-step (batch, label, RNG key, lr row, wd row).  The
        body is ``_make_ftrain`` VERBATIM, so one scan iteration is
        op-for-op one whole step (fp16 skip-step and residual feedback
        included)."""
        ftrain = self._make_ftrain(built, opt_, policy, thr, window)

        def fsuper(gparams, states, residuals, scaler, aux, consts,
                   datas, labels, keys, lrs, wds, ts):
            def body(carry, xs):
                gp, st, res, sc, ax, t = carry
                data, label, key, lr, wd = xs
                loss, nax, nparams, nstates, nres, nsc, nt = ftrain(
                    gp, st, res, sc, ax, consts, data, label, key,
                    lr, wd, t)
                return (nparams, nstates, nres, nsc, nax, nt), loss

            carry, losses = jax.lax.scan(
                body, (gparams, states, residuals, scaler, aux, ts),
                (datas, labels, keys, lrs, wds), length=k)
            ngp, nst, nres, nsc, nax, nts = carry
            return losses, nax, ngp, nst, nres, nsc, nts

        mesh = self.mesh
        if mesh is None or mesh.size <= 1:
            return jax.jit(fsuper, donate_argnums=(0, 1, 2, 3, 4))
        # same rule as WholeStepCompiler._build_fn: GSPMD may pick
        # different output shardings for the scan carry than its inputs,
        # and a donated buffer whose output layout differs cannot alias.
        # Pin every donated output to its input's committed
        # NamedSharding (same-shape state leaves shard like their
        # weight, everything else replicates).
        from jax.lax import with_sharding_constraint as _wsc
        from jax.sharding import NamedSharding, PartitionSpec
        params = built["params"]
        gnames = built["gnames"]
        psh = {n: params[n].sharding for n in gnames}
        repl = NamedSharding(mesh, PartitionSpec())

        def _pin_state(s, wsh, wshape):
            if s is None:
                return None
            if isinstance(s, (tuple, list)):
                return type(s)(_pin_state(x, wsh, wshape) for x in s)
            tgt = wsh if tuple(s.shape) == wshape and wsh is not None \
                else repl
            return _wsc(s, tgt)

        def fshard(gparams, states, residuals, scaler, aux, consts,
                   datas, labels, keys, lrs, wds, ts):
            (losses, nax, ngp, nst, nres, nsc,
             nts) = fsuper(gparams, states, residuals, scaler, aux,
                           consts, datas, labels, keys, lrs, wds, ts)
            ngp = {n: _wsc(v, psh[n] if psh[n] is not None else repl)
                   for n, v in ngp.items()}
            nst = [_pin_state(s, psh[gnames[j]],
                              tuple(gparams[gnames[j]].shape))
                   for j, s in enumerate(nst)]
            nax = {n: _wsc(v, repl) for n, v in nax.items()}
            nsc = {n: _wsc(v, repl) for n, v in nsc.items()} \
                if isinstance(nsc, dict) else nsc
            return losses, nax, ngp, nst, nres, nsc, nts

        return jax.jit(fshard, donate_argnums=(0, 1, 2, 3, 4))

    # -- per-superstep driver ------------------------------------------------
    def _run_super(self, built, datas, labels, bs, policy, k, stacked):
        tr = self.trainer
        # ONE chaos site per superstep, fired before the schedule
        # counters advance and before any donated buffer is touched: an
        # injected raise is a cleanly-retryable failed SUPERSTEP (the
        # supervisor's replay window holds whole K-batch groups)
        _fi_fire("trainer.step", step=tr._step_id)
        upd = tr._updaters[0]
        opt_ = upd.optimizer
        idx = built["idx"]
        if policy != "f32" and any(d != "float32"
                                   for _, d in built["sig"]):
            raise _AmpIneligible(
                f"MXNET_AMP={policy} needs float32 master weights")
        gc = getattr(tr._kv, "_gc", None) if tr._kv is not None else None
        thr = gc.threshold if gc is not None else None
        if thr is not None and self.mesh is not None \
                and self.mesh.size > 1:
            # same rule as WholeStepCompiler._run: GSPMD collectives
            # replace the bucketed allreduce on a real mesh (the scan
            # body is the shared tracer, so the two modes must agree)
            if not self._mesh_comp_warned:
                self._mesh_comp_warned = True
                from ..parallel.mesh import mesh_signature
                logger.warning(
                    "2-bit gradient compression is disabled inside the "
                    "superstep program on a multi-device mesh (%s) — "
                    "GSPMD collectives replace the bucketed allreduce",
                    mesh_signature(self.mesh))
            thr = None
        if built["bk"] is None:
            # every trainable param is a sparse embedding (ISSUE 20):
            # no dense buckets exist, so compression has nothing to act
            # on — the sparse leg's row grads never flatten
            thr = None
        residuals = []
        if thr is not None:
            if tr._residuals is None:
                tr._residuals = tr._init_residuals(built["bk"])
            residuals = tr._residuals
        scaler = {}
        window = 0
        if policy == "fp16":
            st = tr._ensure_scaler()
            window = st["window"]
            scaler = {"scale": st["scale"], "good": st["good"]}

        opt_.rescale_grad = tr._scale / bs
        # advance the schedule counters K times host-side, capturing
        # the per-step lr/wd rows EXACTLY as K sequential _run calls
        # would see them (stacked (K, n) xs — schedules that move
        # mid-superstep stay bitwise-exact); roll all K back if the
        # build/dispatch fails so the fallback's own counting starts
        # clean
        prev_nu = opt_.num_update
        prev_counts = {i: opt_._index_update_count.get(i) for i in idx}
        lr_rows, wd_rows = [], []
        ts = counts0 = None
        try:
            for s in range(k):
                for i in idx:
                    opt_._update_count(i)
                if s == 0:
                    # after the FIRST bump: the same seeding point one
                    # sequential step uses, so the device applied-step
                    # counter (and any checkpointed pending ts) carries
                    # over identically
                    _l, _w, ts, counts0 = self._hyper_arrays(opt_, idx)
                lr_rows.append(tuple(opt_._get_lr(i) for i in idx))
                wd_rows.append(tuple(opt_._get_wd(i) for i in idx))
            return self._dispatch_super(
                built, opt_, upd, policy, thr, window, scaler, residuals,
                datas, labels, bs, k, stacked, lr_rows, wd_rows, ts,
                counts0)
        except Exception:
            opt_.num_update = prev_nu
            for i, c in prev_counts.items():
                if c is None:
                    opt_._index_update_count.pop(i, None)
                else:
                    opt_._index_update_count[i] = c
            raise

    def _stage(self, datas, labels, k, stacked):
        """Device-stage the K batches as (K, ...) stacked arrays.  A
        list input asks the HBM ledger for headroom BEFORE staging (the
        arbitration point the multi-model registry also uses); refusal
        demotes this call to K=1."""
        if stacked:
            return datas._data, labels._data, datas.context
        need = sum(int(_np.prod(a.shape)) *
                   _np.dtype(str(a.dtype)).itemsize
                   for a in (datas[0], labels[0])) * k
        if _memory.ENABLED and not _memory.ensure_headroom(
                need, why=f"superstep staging (K={k} batches)"):
            raise _SuperIneligible(
                f"HBM ledger refused {need} bytes of headroom for "
                f"staging K={k} batches")
        return (jnp.stack([d._data for d in datas]),
                jnp.stack([l._data for l in labels]), datas[0].context)

    def _dispatch_super(self, built, opt_, upd, policy, thr, window,
                        scaler, residuals, datas, labels, bs, k, stacked,
                        lr_rows, wd_rows, ts, counts0):
        tr = self.trainer
        params = built["params"]
        gnames = built["gnames"]
        idx = built["idx"]
        mesh = self.mesh
        if mesh is not None:
            from ..parallel import mesh as _pmesh
            daxis = _pmesh.data_axis(mesh)
            dsize = int(mesh.shape[daxis])
            if bs % dsize != 0:
                raise _ShardIneligible(
                    f"batch of {bs} does not divide the mesh's "
                    f"{daxis} axis (size {dsize})")
        datas_j, labels_j, ctx = self._stage(datas, labels, k, stacked)
        if mesh is not None:
            # committed placement of the staged (K, batch, ...) stacks:
            # the scan axis replicates, the batch axis shards — jit
            # reads in_shardings off these and compiles the sharded
            # scan program (still 1 dispatch per K steps)
            from jax.sharding import NamedSharding, PartitionSpec
            ssh = NamedSharding(mesh, PartitionSpec(None, daxis))
            datas_j = jax.device_put(datas_j, ssh)  # graft-lint: disable=memory-hygiene
            labels_j = jax.device_put(labels_j, ssh)  # graft-lint: disable=memory-hygiene
        # stacked (K, n) lr/wd rows with a last-value cache — constant
        # schedules re-upload nothing after the first superstep
        lrk, wdk = tuple(lr_rows), tuple(wd_rows)
        sc = self._stack_cache
        if sc.get("lr_key") != lrk:
            sc["lr_key"] = lrk
            sc["lr"] = jnp.asarray(_np.array(lrk, _np.float32))  # graft-lint: disable=host-sync
        if sc.get("wd_key") != wdk:
            sc["wd_key"] = wdk
            sc["wd"] = jnp.asarray(_np.array(wdk, _np.float32))  # graft-lint: disable=host-sync
        lrs, wds = sc["lr"], sc["wd"]
        gparams = {n: params[n].list_data()[0]._data for n in gnames}
        consts = {n: params[n].list_data()[0]._data
                  for n in built["cnames"]}
        aux = {n: params[n].list_data()[0]._data
               for n in built["aux_names"]}
        if mesh is not None and mesh.size > 1:
            # same restore-path conformance as WholeStepCompiler._dispatch:
            # rehydrated states land on the default device; pull them
            # back onto their weights' committed NamedSharding
            from ..optimizer import _conform_state_sharding
            for j, n in enumerate(gnames):
                upd.states[idx[j]] = _conform_state_sharding(
                    upd.states[idx[j]], params[n].list_data()[0])
        svals = [upd._state_data(upd.states[i]) for i in idx]

        upd.dtype_policy = policy
        pol_key = policy if policy != "fp16" else f"fp16/w{window}"
        from ..parallel.mesh import mesh_signature as _mesh_sig
        msig = _mesh_sig(mesh)
        key = ("superstep", pol_key, type(opt_).__name__,
               opt_.fused_hyper_key(), idx,
               tuple(d for _, d in built["sig"]),
               built["uid"], thr,
               built["bk"].sizes if thr is not None else None,
               jax.tree_util.tree_structure(svals), k, msig)
        fn = upd.lookup_program(
            key, lambda: self._build_super_fn(built, opt_, policy, thr,
                                              window, k))
        note_key = (key, tuple(datas_j.shape), tuple(labels_j.shape))
        if _introspect.ENABLED and note_key not in self._noted_keys:
            self._noted_keys.add(note_key)
            import hashlib
            # K folds into the signature: the noted flops are the SCAN
            # program's (K x one step — XLA's cost model counts the
            # body per iteration), so the perf baseline and MFU
            # numerator track the superstep length honestly
            sig = hashlib.sha1(repr(
                (built["sig"], type(opt_).__name__, policy,
                 thr is not None, tuple(datas_j.shape),
                 tuple(labels_j.shape), k,
                 msig)).encode()).hexdigest()[:16]
            contracts = {
                "donate_argnums": (0, 1, 2, 3, 4),
                "donated_leaves": len(jax.tree_util.tree_leaves(
                    (gparams, svals, residuals, scaler, aux))),
                "amp": policy,
                "host_callbacks": 0,
                "buckets": len(built["bk"].sizes)
                if thr is not None else 0,
                "superstep_k": k,
            }
            if mesh is not None and mesh.size > 1:
                # same GSPMD plan the whole-step program declares: the
                # scan body carries the collectives, so each sized axis
                # shows at least one in the lowered HLO
                contracts["mesh_axes"] = {
                    a: int(mesh.shape[a]) for a in mesh.axis_names}
                contracts["collective_plan"] = {
                    a: 1 for a in mesh.axis_names
                    if int(mesh.shape[a]) > 1}
            else:
                contracts["collectives"] = 0
            _introspect.note_jit(
                "superstep", fn, gparams, svals, residuals, scaler, aux,
                consts, datas_j, labels_j,
                jnp.stack([jax.random.PRNGKey(i) for i in range(k)]),
                lrs, wds, ts, signature=sig, contracts=contracts)

        # chaos site for transient device loss at the dispatch boundary
        _fi_fire("device.unavailable", step=tr._step_id)
        from .. import random as _random
        # K keys drawn from the SAME next_key() sequence K sequential
        # steps would consume — the bitwise-parity contract includes
        # the RNG stream (dropout etc.)
        keys = jnp.stack([_random.next_key() for _ in range(k)])
        on = _metrics.ENABLED
        d0 = _metrics.step_dispatches() if on else 0.0
        if on:
            _metrics.XLA_LAUNCHES.inc(kind="superstep")
            _metrics.OPTIMIZER_STEPS.inc(float(k))
        try:
            with trace_span("superstep", cat="trainer"), \
                    _flight.phase_span("superstep", cat="step",
                                       step=tr._step_id, watch=True,
                                       mem=True, labels={"k": k}), \
                    _memory.oom_guard("superstep.step"):
                losses, new_aux, new_p, new_s, new_res, new_scaler, \
                    nts = fn(gparams, svals, residuals, scaler, aux,
                             consts, datas_j, labels_j, keys, lrs, wds,
                             ts)
        except BaseException:
            if _san.ENABLED:
                _san.poison_donated(
                    "superstep",
                    *[params[n].list_data() for n in gnames],
                    *[params[n].list_data()
                      for n in built["aux_names"]],
                    *[upd.states[i] for i in idx])
            raise
        tr._step_id += k
        if on:
            delta = _metrics.step_dispatches() - d0
            # the demotion tripwire: 1 dispatch per SUPERSTEP when the
            # scan runs, K when silently demoted to per-step dispatches
            # — the perf sentinel's dispatch baseline reads this gauge
            # for the "superstep" phase
            _metrics.SUPERSTEP_DISPATCHES.set(delta)
            _metrics.TRAINER_STEP_DISPATCHES.set(delta / float(k))
        if _introspect.ENABLED:
            _introspect.sentinel_tick("superstep")
        if _journal.ENABLED:
            _journal.maybe_milestone(tr._step_id, source="superstep")

        # commit: counts advanced K times host-side, so the hyper
        # cache's next-step expectation is counts0 + K (commit adds 1)
        self._commit_outputs(built, upd, policy, thr, new_p, new_aux,
                             new_s, new_res, new_scaler, nts,
                             tuple(c + k - 1 for c in counts0))
        self._ran = True
        self._super_ran = True
        return NDArray(losses, ctx)
