"""Ahead-of-time model export (parity role: `amalgamation/` + the predict
C API deployment story — `include/mxnet/c_predict_api.h`).

The reference shipped models to phones by amalgamating the runtime into one
C file and loading symbol JSON + params.  The TPU-native deployment artifact
is a serialized StableHLO program: `export_model` traces a bound model
(symbol + params) once and serializes it with `jax.export`; `load_model`
deserializes and runs it on any host with jax — no framework code needed at
serving time.  Together with `mxnet_tpu.predictor` this covers both of the
reference's deployment surfaces.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import numpy as _np

from .base import MXNetError, atomic_write
from . import ndarray as nd
from .ndarray import NDArray
from . import symbol as sym_mod


def export_model(symbol, arg_params: Dict, aux_params: Dict,
                 input_shapes: Dict[str, tuple], path: str,
                 input_dtypes: Optional[Dict[str, str]] = None) -> None:
    """Serialize symbol+params into `path` (a directory):
    `program.shlo` (StableHLO bytes), `params.nd`, `meta.json`."""
    import jax
    import jax.numpy as jnp
    from jax import export as jexport
    from .symbol.graph import GraphPlan

    plan = GraphPlan(symbol)
    plan.specialize_init_shapes(dict(input_shapes))
    params = {k: (v._data if isinstance(v, NDArray) else jnp.asarray(v))
              for k, v in arg_params.items()}
    auxs = {k: (v._data if isinstance(v, NDArray) else jnp.asarray(v))
            for k, v in aux_params.items()}
    input_names = sorted(input_shapes)
    key = jax.random.PRNGKey(0)

    def fn(*inputs):
        d = dict(params)
        d.update(dict(zip(input_names, inputs)))
        outs, _ = plan.run(d, auxs, key, False)
        return tuple(outs)

    dtypes = input_dtypes or {}
    args = [jax.ShapeDtypeStruct(tuple(input_shapes[n]),
                                 _np.dtype(dtypes.get(n, "float32")))
            for n in input_names]
    exported = jexport.export(jax.jit(fn))(*args)
    os.makedirs(path, exist_ok=True)
    # every artifact commits via tmp+os.replace (base.atomic_write):
    # re-exporting over a served model directory must never leave a
    # half-written program next to the old params
    atomic_write(os.path.join(path, "program.shlo"), exported.serialize())
    nd.save(os.path.join(path, "params.nd"),
            {f"arg:{k}": NDArray(v) for k, v in params.items()} |
            {f"aux:{k}": NDArray(v) for k, v in auxs.items()})
    atomic_write(os.path.join(path, "meta.json"), json.dumps(
        {"input_names": input_names,
         "input_shapes": {k: list(v) for k, v in input_shapes.items()},
         "outputs": symbol.list_outputs()}))
    symbol.save(os.path.join(path, "symbol.json"))


class ExportedModel:
    """Runs a serialized program; params are baked into the export."""

    def __init__(self, path: str):
        from jax import export as jexport
        with open(os.path.join(path, "program.shlo"), "rb") as f:
            self._exported = jexport.deserialize(f.read())
        with open(os.path.join(path, "meta.json")) as f:
            self.meta = json.load(f)
        self.input_names = self.meta["input_names"]

    def __call__(self, *inputs, **named):
        import jax.numpy as jnp
        if named:
            if inputs:
                raise MXNetError(
                    "pass inputs either positionally (in input_names order) "
                    "or all by name, not both")
            missing = [n for n in self.input_names if n not in named]
            if missing:
                raise MXNetError(f"missing inputs {missing}; expected "
                                 f"{self.input_names}")
            inputs = [named[n] for n in self.input_names]
        vals = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in inputs]
        outs = self._exported.call(*vals)
        return [NDArray(o) for o in outs]


def load_model(path: str) -> ExportedModel:
    return ExportedModel(path)


def export_checkpoint(prefix: str, epoch: int,
                      input_shapes: Dict[str, tuple], path: str) -> None:
    """Convenience: export straight from a Module checkpoint
    (prefix-symbol.json + prefix-%04d.params)."""
    from . import model as model_mod
    symbol, arg_params, aux_params = model_mod.load_checkpoint(prefix, epoch)
    # label inputs aren't serving inputs: bind them as zero constants
    arg_names = symbol.list_arguments()
    missing = [n for n in arg_names
               if n not in arg_params and n not in input_shapes]
    if missing:
        arg_shapes, _, _ = symbol.infer_shape_partial(**input_shapes)
        inferred = dict(zip(arg_names, arg_shapes or []))
        for name in missing:
            shp = inferred.get(name)
            if shp is None:
                raise MXNetError(f"cannot infer shape for input '{name}'")
            arg_params[name] = nd.zeros(shp)
    export_model(symbol, arg_params, aux_params, input_shapes, path)
