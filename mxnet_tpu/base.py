"""Core shared definitions: errors, dtype tables, registries, small utils.

Reference parity: plays the role of `python/mxnet/base.py` plus the
dmlc-core capabilities mxnet consumed (`dmlc::Parameter` declarative config,
`dmlc::Registry`, env-var access — SURVEY.md §2.1 "empty-submodule
capabilities").  No ctypes FFI is needed: the "C API" boundary of the
reference (src/c_api/) is replaced by JAX/XLA python-native calls.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as _np

import jax as _jax

# float64 NDArrays are part of the reference API surface (mx.nd.array keeps
# numpy float64); TPU code paths stay f32/bf16 — x64 only widens CPU-side use.
_jax.config.update("jax_enable_x64", True)


def _apply_cpu_only_guard():
    """When the user forces CPU (JAX_PLATFORMS=cpu), deregister any TPU
    plugin backend factory: some plugins (axon) register in sitecustomize
    and contact the device tunnel on the first backends() call even for
    CPU-only runs — an unreachable tunnel would hang examples/tools/tests.
    tests/conftest.py and __graft_entry__ route through the same guard."""
    platforms = [x.strip() for x in
                 os.environ.get("JAX_PLATFORMS", "").split(",") if x.strip()]
    if platforms != ["cpu"]:
        return False
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    try:
        _jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return True


_apply_cpu_only_guard()


def _maybe_init_distributed():
    """Join the jax.distributed cluster when launched by tools/launch.py
    (MXT_COORDINATOR / MXT_NUM_PROC / MXT_PROC_ID env contract — the
    redesign of ps-lite's DMLC_* tracker env, SURVEY.md §2.3).  Must run
    at import, before any backend is created."""
    coord = os.environ.get("MXT_COORDINATOR")
    nproc = int(os.environ.get("MXT_NUM_PROC", "1") or 1)
    if not coord or nproc <= 1:
        return
    pid = os.environ.get("MXT_PROC_ID")
    if pid is None:
        # mpirun placement (tools/launch.py --launcher mpi): the rank
        # comes from the MPI runtime's own env.  No rank var at all is
        # a misconfiguration — every process would claim rank 0 and the
        # coordinator would wait forever; fail fast instead.
        pid = (os.environ.get("OMPI_COMM_WORLD_RANK")
               or os.environ.get("PMI_RANK")
               or os.environ.get("PMIX_RANK"))
        if pid is None:
            raise MXNetError(
                "MXT_NUM_PROC=%d but no process rank found: set "
                "MXT_PROC_ID (tools/launch.py does) or launch under "
                "mpirun (OMPI_COMM_WORLD_RANK/PMI_RANK/PMIX_RANK)"
                % nproc)
    pid = int(pid)
    try:
        _jax.distributed.initialize(coord, nproc, pid)
    except RuntimeError as e:
        # tolerate ONLY double-init (e.g. the TPU pod runtime already
        # joined); an unreachable coordinator must fail fast — swallowing
        # it would silently degrade to un-synchronized workers
        if "already initialized" in str(e).lower():
            return
        raise MXNetError(
            f"jax.distributed.initialize(coordinator={coord}, "
            f"num_processes={nproc}, process_id={pid}) failed: {e}") from e


_maybe_init_distributed()


def _init_crash_handler():
    """Library init (parity: src/initialize.cc:33-50 — SIGSEGV backtrace
    handler + dmlc logging init): a crash in any thread (native engine
    workers included) dumps python tracebacks for every thread.  Disable
    with MXNET_USE_SIGNAL_HANDLER=0."""
    if os.environ.get("MXNET_USE_SIGNAL_HANDLER", "1") == "0":
        return
    import faulthandler
    try:
        faulthandler.enable(all_threads=True)
    except Exception:
        pass  # non-main-thread import or closed stderr


_init_crash_handler()


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: mxnet.base.MXNetError)."""


# ---------------------------------------------------------------------------
# dtype tables (parity: python/mxnet/base.py _DTYPE_NP_TO_MX / _DTYPE_MX_TO_NP)
# TPU-native addition: bfloat16 is first-class (the MXU native dtype).
# ---------------------------------------------------------------------------
try:
    import ml_dtypes as _mld
    bfloat16 = _np.dtype(_mld.bfloat16)
except ImportError:  # pragma: no cover
    bfloat16 = None

_DTYPE_NP_TO_MX: Dict[Any, int] = {
    None: -1,
    _np.dtype(_np.float32): 0,
    _np.dtype(_np.float64): 1,
    _np.dtype(_np.float16): 2,
    _np.dtype(_np.uint8): 3,
    _np.dtype(_np.int32): 4,
    _np.dtype(_np.int8): 5,
    _np.dtype(_np.int64): 6,
    _np.dtype(_np.bool_): 7,
}
if bfloat16 is not None:
    _DTYPE_NP_TO_MX[bfloat16] = 12

_DTYPE_MX_TO_NP: Dict[int, Any] = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

_STORAGE_TYPE_STR_TO_ID = {"undefined": -1, "default": 0, "row_sparse": 1, "csr": 2}
_STORAGE_TYPE_ID_TO_STR = {v: k for k, v in _STORAGE_TYPE_STR_TO_ID.items()}


def np_dtype(dtype) -> _np.dtype:
    """Canonicalize a user-supplied dtype (str/np.dtype/type) to np.dtype."""
    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16":
        if bfloat16 is None:
            raise MXNetError("bfloat16 requires ml_dtypes")
        return bfloat16
    return _np.dtype(dtype)


def getenv(name: str, default):
    """Typed env lookup (parity: dmlc::GetEnv). MXNET_* envs keep their names."""
    val = os.environ.get(name)
    if val is None:
        return default
    ty = type(default)
    if ty is bool:
        return val not in ("0", "false", "False", "")
    return ty(val)


_COMPILE_CACHE_WIRED = False
_COMPILE_CACHE_FAILED = False


def maybe_enable_compile_cache() -> bool:
    """Wire JAX's persistent compilation cache to MXNET_COMPILE_CACHE_DIR.

    Every jit/AOT compile (training executors AND serving buckets) then
    lands on disk, so a process restart — the serving case: a rolling
    redeploy must not pay the full bucket-lattice compile again — loads
    executables instead of recompiling.  Checked lazily at executor /
    serving construction (not import) so the env can be set after
    `import mxnet_tpu`; idempotent and near-free once wired.  Returns
    whether the cache is active."""
    global _COMPILE_CACHE_WIRED, _COMPILE_CACHE_FAILED
    if _COMPILE_CACHE_WIRED:
        return True
    if _COMPILE_CACHE_FAILED:
        return False  # warned once already; don't retry per bind
    cache_dir = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    if not cache_dir:
        return False
    try:
        _jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip "cheap" compiles — serving buckets are
        # exactly the small programs the restart win comes from, so
        # persist everything
        for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                          ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                _jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — knob name drifts by version
                pass
    except Exception as e:  # noqa: BLE001
        import warnings
        warnings.warn(f"MXNET_COMPILE_CACHE_DIR={cache_dir!r} could not be "
                      f"wired: {e}")
        _COMPILE_CACHE_FAILED = True
        return False
    _COMPILE_CACHE_WIRED = True
    return True


def atomic_write(fname: str, data) -> None:
    """Crash-atomic small-file write: temp file in the SAME directory,
    then one ``os.replace`` — a crash mid-write never corrupts an
    existing file at ``fname``.  str writes text, bytes writes binary.
    (The checkpoint subsystem's directory-level commit lives in
    mxnet_tpu/checkpoint/layout.py; this is the single-file variant
    shared by symbol/params/states writers.)"""
    tmp = f"{fname}.tmp-{os.getpid()}"
    mode = "w" if isinstance(data, str) else "wb"
    with open(tmp, mode) as f:
        f.write(data)
    os.replace(tmp, fname)


def unique_path(directory: str, stem: str, ext: str, clock=None) -> str:
    """Collision-free timestamped file path — the ONE filename policy
    every dump writer (``profiler.dump_profile`` autosnapshots,
    ``observability.flight.dump``) shares:
    ``<dir>/<stem>-<UTC stamp>-<pid>[.N]<ext>``.

    ``clock`` is the injectable epoch-seconds source (default
    ``time.time``) so tests exercise the collision suffix
    deterministically instead of racing ambient wall-clock."""
    import time as _time
    t = (clock or _time.time)()
    stamp = _time.strftime("%Y%m%d-%H%M%S", _time.gmtime(t))
    base_name = f"{stem}-{stamp}-{os.getpid()}"
    path = os.path.join(directory, base_name + ext)
    n = 1
    while os.path.exists(path):
        path = os.path.join(directory, f"{base_name}.{n}{ext}")
        n += 1
    return path


# ---------------------------------------------------------------------------
# Generic registry (parity: dmlc::Registry / python/mxnet/registry.py)
# ---------------------------------------------------------------------------
class Registry:
    """Name → object registry with alias support."""

    def __init__(self, kind: str):
        self.kind = kind
        self._map: Dict[str, Any] = {}

    def register(self, obj=None, name: Optional[str] = None):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or o.name).lower()
            self._map[key] = o
            return o
        return _do(obj) if obj is not None else _do

    def get(self, name: str):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered; known: {sorted(self._map)}")
        return self._map[key]

    def find(self, name: str):
        return self._map.get(name.lower())

    def create(self, name_or_obj, *args, **kwargs):
        if isinstance(name_or_obj, str):
            return self.get(name_or_obj)(*args, **kwargs)
        return name_or_obj

    def list(self) -> List[str]:
        return sorted(self._map)


# ---------------------------------------------------------------------------
# Declarative op/iterator parameter schema
# (parity: dmlc::Parameter<T> — DMLC_DECLARE_PARAMETER structs that every
#  reference op uses, e.g. src/kvstore/gradient_compression.h:43-48)
# ---------------------------------------------------------------------------
@dataclass
class Arg:
    name: str
    type: Callable = float
    default: Any = None
    required: bool = False
    doc: str = ""


class ParamSchema:
    """Validates/normalizes kwargs for an op into a canonical hashable tuple.

    `open_schema=True` passes unknown kwargs through as strings — the
    `Custom` op forwards them to the user's CustomOpProp constructor
    (parity: custom.cc keeps all kwargs as char** for the python callback).
    """

    def __init__(self, args: List[Arg], open_schema: bool = False):
        self.args = {a.name: a for a in args}
        self.open_schema = open_schema

    @staticmethod
    def _canon(ty, v):
        if v is None:
            return None
        if ty in (tuple, "shape"):
            if isinstance(v, str):
                v = eval(v, {"__builtins__": {}})  # "(2, 2)" from string configs
            if isinstance(v, (int, _np.integer)):
                return (int(v),)
            # None entries stay None (open-ended slice bounds, e.g.
            # _slice_assign begin=(None, 1))
            return tuple(None if x is None else int(x) for x in v)
        if ty == "floats":  # float tuple (anchor sizes/ratios, variances)
            if isinstance(v, str):
                v = eval(v, {"__builtins__": {}})
            if isinstance(v, (int, float, _np.integer, _np.floating)):
                return (float(v),)
            return tuple(float(x) for x in v)
        if ty is bool:
            if isinstance(v, str):
                return v.lower() in ("1", "true", "yes")
            return bool(v)
        if ty is int:
            return int(v)
        if ty is float:
            return float(v)
        if ty is str:
            return str(v)
        return ty(v)

    def normalize(self, kwargs: Dict[str, Any]) -> Tuple[Tuple[str, Any], ...]:
        out = {}
        for k, v in kwargs.items():
            if k not in self.args:
                if self.open_schema:
                    out[k] = str(v)
                    continue
                raise MXNetError(f"unknown argument '{k}'; expected {sorted(self.args)}")
            out[k] = self._canon(self.args[k].type, v)
        for a in self.args.values():
            if a.name not in out:
                if a.required:
                    raise MXNetError(f"required argument '{a.name}' missing")
                out[a.name] = self._canon(a.type, a.default) if a.default is not None else a.default
        return tuple(sorted(out.items()))


class _ThreadLocalStack(threading.local):
    """Per-thread stack used by with-scopes (Context, AttrScope, NameManager)."""

    def __init__(self):
        self.stack: List[Any] = []

    def top(self):
        return self.stack[-1] if self.stack else None

    def push(self, v):
        self.stack.append(v)

    def pop(self):
        return self.stack.pop()
