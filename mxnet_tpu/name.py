"""NameManager (parity: python/mxnet/name.py) — automatic unique naming for
symbols and gluon blocks."""
from __future__ import annotations

from typing import Dict, Optional

from .base import _ThreadLocalStack


class NameManager:
    _stack = _ThreadLocalStack()

    def __init__(self):
        self._counter: Dict[str, int] = {}

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    @staticmethod
    def current() -> "NameManager":
        top = NameManager._stack.top()
        if top is None:
            return _DEFAULT
        return top

    def __enter__(self):
        NameManager._stack.push(self)
        return self

    def __exit__(self, *exc):
        NameManager._stack.pop()


class Prefix(NameManager):
    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


_DEFAULT = NameManager()
