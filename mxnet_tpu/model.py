"""Model checkpoint helpers + BatchEndParam (parity: python/mxnet/model.py).

Checkpoint format parity (model.py:366,396): `prefix-symbol.json` (graph
JSON) + `prefix-%04d.params` (NDArray map with `arg:`/`aux:` key prefixes,
stored via mx.nd.save).  The deprecated FeedForward API is represented by
Module (the reference itself forwards users there).
"""
from __future__ import annotations

from collections import namedtuple
from typing import Dict, Tuple

from .base import MXNetError
from .context import cpu
from . import ndarray as nd
from . import symbol as sym

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict, reference_format: bool = False) -> None:
    """Parity: model.save_checkpoint — prefix-symbol.json + prefix-%04d.params.

    reference_format=True writes the .params in the ORIGINAL
    framework's binary container (legacy_format.py V2) so the
    checkpoint serves on a reference installation — load_checkpoint
    here reads both formats transparently.

    Both files are written crash-atomically (temp-in-same-dir +
    os.replace inside nd.save / Symbol.save): a crash mid-save never
    corrupts an existing checkpoint at the same prefix.  For the
    fault-tolerant manager (async saves, CRC validation, retention,
    auto-resume) see mxnet_tpu.checkpoint / docs/checkpointing.md."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    if reference_format:
        nd.save_reference_format(f"{prefix}-{epoch:04d}.params", save_dict)
    else:
        nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix: str, epoch: int):
    """Parity: model.load_checkpoint → (symbol, arg_params, aux_params)."""
    symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def _create_kvstore(kvstore, num_device: int, arg_params):
    """Parity: model.py:_create_kvstore — returns (kv, update_on_kvstore)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and "tpu" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(arg.size for arg in arg_params.values()) \
                    if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise MXNetError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


class FeedForward:
    """Legacy v0.x model API (parity: model.py FeedForward — kept for
    pre-Module user code; delegates to mx.mod.Module, which is the
    supported path).  Supports numpy or DataIter inputs, fit/predict/
    score, save/load checkpoints, and the one-call `create`."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None,
                 allow_extra_params=False, begin_epoch=0, **kwargs):
        from . import initializer as _init
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or _init.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.optimizer_params = kwargs
        self._mod = None

    # -- data plumbing ------------------------------------------------------
    def _as_iter(self, X, y=None, is_train=False):
        from . import io as _io
        import numpy as _np2
        if isinstance(X, _io.DataIter):
            return X
        X = _np2.asarray(X)
        if y is None and is_train:
            raise MXNetError("y is required when X is a numpy array")
        y = _np2.zeros(X.shape[0]) if y is None else _np2.asarray(y)
        return _io.NDArrayIter(X, y, batch_size=min(self.numpy_batch_size,
                                                    X.shape[0]),
                               shuffle=is_train)

    def _init_module(self, it):
        from . import module as _mod
        self._mod = _mod.Module(
            self.symbol,
            data_names=[d.name for d in it.provide_data],
            label_names=[l.name for l in it.provide_label],
            context=self.ctx or cpu())
        self._mod.bind(data_shapes=it.provide_data,
                       label_shapes=it.provide_label, for_training=True)
        self._mod.init_params(self.initializer,
                              arg_params=self.arg_params,
                              aux_params=self.aux_params,
                              allow_missing=self.arg_params is not None,
                              allow_extra=self.allow_extra_params)

    # -- training / inference ----------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", logger=None, monitor=None):
        from . import metric as _metric
        it = self._as_iter(X, y, is_train=True)
        if self._mod is None:
            self._init_module(it)
        if self.epoch_size is not None:
            # reference semantics: bound each epoch at epoch_size batches
            # (non-terminating iterators end their epoch here)
            from .io import ResizeIter
            it = ResizeIter(it, self.epoch_size, reset_internal=False)
        if logger is not None:
            logger.info("Start training with %s",
                        self.ctx if self.ctx is not None else "cpu(0)")
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        self._mod.fit(it, eval_data=eval_data, eval_metric=eval_metric,
                      kvstore=kvstore, optimizer=self.optimizer,
                      optimizer_params=self.optimizer_params,
                      begin_epoch=self.begin_epoch,
                      num_epoch=self.num_epoch or 1,
                      epoch_end_callback=epoch_end_callback,
                      batch_end_callback=batch_end_callback,
                      monitor=monitor)
        self.arg_params, self.aux_params = self._mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        import numpy as _np2
        it = self._as_iter(X)
        if self._mod is None:
            self._init_module(it)
        if reset:
            it.reset()
        outs, datas, labels = [], [], []
        for i, batch in enumerate(it):
            if num_batch is not None and i >= num_batch:
                break
            self._mod.forward(batch, is_train=False)
            pad = batch.pad or 0
            n = batch.data[0].shape[0] - pad
            outs.append(self._mod.get_outputs()[0].asnumpy()[:n])
            if return_data:
                datas.append(batch.data[0].asnumpy()[:n])
                labels.append(batch.label[0].asnumpy()[:n])
        out = _np2.concatenate(outs)
        if return_data:
            return out, _np2.concatenate(datas), _np2.concatenate(labels)
        return out

    def score(self, X, eval_metric="acc", num_batch=None, reset=True):
        from . import metric as _metric
        it = self._as_iter(X)
        if self._mod is None:
            self._init_module(it)
        if reset:
            it.reset()
        if isinstance(eval_metric, str):
            eval_metric = _metric.create(eval_metric)
        self._mod.score(it, eval_metric, num_batch=num_batch)
        return eval_metric.get()[1]

    # -- checkpoints --------------------------------------------------------
    def save(self, prefix, epoch=None):
        epoch = self.num_epoch if epoch is None else epoch
        assert epoch is not None
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None,
               epoch_size=None, optimizer="sgd", initializer=None,
               eval_data=None, eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               **kwargs):
        """Build + fit in one call (parity: FeedForward.create)."""
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback,
                  kvstore=kvstore, logger=logger)
        return model
