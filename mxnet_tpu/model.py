"""Model checkpoint helpers + BatchEndParam (parity: python/mxnet/model.py).

Checkpoint format parity (model.py:366,396): `prefix-symbol.json` (graph
JSON) + `prefix-%04d.params` (NDArray map with `arg:`/`aux:` key prefixes,
stored via mx.nd.save).  The deprecated FeedForward API is represented by
Module (the reference itself forwards users there).
"""
from __future__ import annotations

from collections import namedtuple
from typing import Dict, Tuple

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix: str, epoch: int, symbol, arg_params: Dict,
                    aux_params: Dict) -> None:
    """Parity: model.save_checkpoint — prefix-symbol.json + prefix-%04d.params."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_checkpoint(prefix: str, epoch: int):
    """Parity: model.load_checkpoint → (symbol, arg_params, aux_params)."""
    symbol = sym.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


def _create_kvstore(kvstore, num_device: int, arg_params):
    """Parity: model.py:_create_kvstore — returns (kv, update_on_kvstore)."""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore and "tpu" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(arg.size for arg in arg_params.values()) \
                    if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise MXNetError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore
