"""Optimizers + Updater (parity: python/mxnet/optimizer.py, 1210 LoC).

SGD/Adam/RMSProp/Ftrl dispatch to the fused update operators
(`mxnet_tpu.ops.optimizer_ops`, parity src/operator/optimizer_op.cc) so each
step is one XLA kernel; the rest are composed NDArray math.  Updater state
pickling matches the reference API (set_states/get_states) for
checkpoint/resume and kvstore server-side optimizers.
"""
from __future__ import annotations

import logging
import math
import pickle
from typing import Any, Dict, Optional

import numpy as _np
import jax
import jax.numpy as jnp

from .analysis import hot_path
from .analysis import sanitizer as _san_mod
from .base import MXNetError, Registry, getenv
from . import ndarray as nd
from .ndarray import NDArray
from .faultinject import fire as _fi_fire
from .observability import introspect as _introspect
from .observability import memory as _memory
from .observability import metrics as _metrics
from .observability.tracing import trace_span

_REG = Registry("optimizer")
_logger = logging.getLogger("mxnet_tpu.optimizer")


def cast_like(new, old):
    """Keep weights/states in their own dtype after a compiled step
    (traced lr/wd are strong f32; the per-key path's weak python floats
    did this implicitly).  Tolerant of None and nested tuple states.
    Shared by FusedUpdater.update_all and the gluon whole-step compiler
    — their bitwise-parity contract depends on identical casting."""
    if new is None or old is None:
        return new
    if isinstance(old, (tuple, list)):
        return type(old)(cast_like(n, o) for n, o in zip(new, old))
    return new.astype(old.dtype) if hasattr(old, "dtype") else new


def _rows_of(arr, rows):
    """Gather arr[rows] without densifying rsp storage (shared gather in
    ndarray.sparse — same semantics as KVStore.row_sparse_pull)."""
    from .ndarray.sparse import gather_rows
    return gather_rows(arr, rows)


def _write_rows(arr, rows, new_rows) -> None:
    """arr[rows] = new_rows, rows-only for rsp storage (an rsp weight is
    never materialized dense on the optimizer hot path)."""
    from .ndarray.sparse import RowSparseNDArray
    if isinstance(arr, RowSparseNDArray):
        arr._upsert_rows(rows, new_rows)
    else:
        arr._set_data(arr._data.at[jnp.asarray(rows)].set(new_rows))


def _is_low_prec(dtype) -> bool:
    """float16/bfloat16 weights get fp32 master copies under multi_precision
    (parity: optimizer_op.cc mp_sgd_* — bf16 is the TPU-native low precision)."""
    return _np.dtype(dtype).name in ("float16", "bfloat16")


class Optimizer:
    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult: Dict[Any, float] = {}
        self.wd_mult: Dict[Any, float] = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count: Dict[Any, int] = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym is not None \
            else ({}, [])
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- registry -----------------------------------------------------------
    @staticmethod
    def register(klass):
        _REG.register(klass)
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.get(name)(**kwargs)

    # -- state --------------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and _is_low_prec(weight.dtype):
            from .ndarray.sparse import RowSparseNDArray
            if isinstance(weight, RowSparseNDArray):
                # rows-only fp32 master: rows present now, new rows
                # upserted by the rsp update path — never the dense
                # O(vocab) copy (parity: mp SGDUpdateRspRspImpl)
                w32 = RowSparseNDArray(
                    weight._indices, weight._values.astype(jnp.float32),
                    weight.shape, weight.context, _dedup=False)
            else:
                w32 = weight.astype(_np.float32)
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and _is_low_prec(weight.dtype):
            inner, w32 = state
            g32 = grad.astype(_np.float32)
            self.update(index, w32, g32, inner)
            w32.copyto(weight)
        else:
            self.update(index, weight, grad, state)

    # -- fused multi-tensor path ---------------------------------------------
    # The TPU analog of the reference's engine op-bulking
    # (src/executor/graph_executor.cc:1350): FusedUpdater traces fused_step
    # for EVERY parameter into ONE jitted XLA program per training step, so
    # Module.update / Trainer.step issue O(1) dispatches instead of O(#params).
    fused = False  # subclasses with a pure fused_step set True
    # True when fused_step itself implements the fp32-master path (SGD's
    # mp_sgd_* kernels); otherwise _fused_step_mp wraps any fused_step with
    # the generic master-weight recipe (parity: update_multi_precision).
    fused_handles_mp = False

    def fused_hyper_key(self):
        """Static hyperparameters baked into the fused trace (cache key)."""
        return (self.rescale_grad, self.clip_gradient)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        """Pure single-param step on jax values: returns (new_weight,
        new_state).  `lr`/`wd` are traced f32 scalars, `t` the traced update
        count (for bias correction); everything else is baked static."""
        raise NotImplementedError

    def _fused_step_mp(self, index, weight, grad, state, lr, wd, t):
        """fused_step with generic multi-precision handling: low-precision
        weights step their fp32 master copy and cast back (parity:
        update_multi_precision)."""
        if self.multi_precision and _is_low_prec(weight.dtype) \
                and not self.fused_handles_mp:
            inner, w32 = state
            nw32, ninner = self.fused_step(index, w32,
                                           grad.astype(jnp.float32), inner,
                                           lr, wd, t)
            return nw32.astype(weight.dtype), (ninner, nw32)
        return self.fused_step(index, weight, grad, state, lr, wd, t)

    def _clip(self, g):
        if self.clip_gradient is not None:
            return jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def _fused_common(self, lr, wd, **extra):
        p = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
             "clip_gradient": self.clip_gradient
             if self.clip_gradient is not None else -1.0}
        p.update(extra)
        return p

    # -- lr/wd plumbing ------------------------------------------------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set lr directly")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        attr, arg_names = self.sym_info
        for name in arg_names:
            if name in attr and "__lr_mult__" in attr[name]:
                self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        attr, arg_names = self.sym_info
        for name in arg_names:
            if name in attr and "__wd_mult__" in attr[name]:
                self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index) -> float:
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index) -> float:
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_kwargs(self):
        kw = dict(rescale_grad=self.rescale_grad)
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum and multi-precision (parity: optimizer.py:435)."""

    fused = True
    fused_handles_mp = True

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.multi_precision and _is_low_prec(weight.dtype):
            return self.create_state_multi_precision(index, weight)
        if self.momentum == 0.0:
            return None
        if getattr(weight, "stype", "default") == "row_sparse":
            # rsp weight gets an rsp momentum (parity: optimizer.py SGD
            # create_state uses stype=weight.stype) — O(nnz), not O(vocab)
            from .ndarray.sparse import zeros_sparse
            return zeros_sparse("row_sparse", weight.shape,
                                ctx=weight.context, dtype=weight.dtype)
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.momentum,
                self.multi_precision)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        from .ops.registry import OP_REGISTRY as _K
        p = self._fused_common(lr, wd, momentum=self.momentum)
        if self.multi_precision and _is_low_prec(weight.dtype):
            mom, w32 = state
            if self.momentum != 0.0:
                nw, nmom, nw32 = _K["mp_sgd_mom_update"].fn(
                    p, weight, grad, mom, w32)
                return nw, (nmom, nw32)
            nw, nw32 = _K["mp_sgd_update"].fn(p, weight, grad, w32)
            return nw, (None, nw32)
        if self.momentum != 0.0:
            nw, nmom = _K["sgd_mom_update"].fn(p, weight, grad, state)
            return nw, nmom
        return _K["sgd_update"].fn(p, weight, grad), None

    def _update_impl(self, index, weight, grad, state, multi_precision):
        """One count bump + one fused kernel (parity: optimizer.py SGD
        _update_impl — update/update_multi_precision share it so num_update
        advances exactly once per step)."""
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            # row-sparse lazy update: ONLY rows present in the gradient
            # step (incl. their wd term) — parity: optimizer_op.cc
            # SGDUpdateRspRspImpl / SGDMomUpdateRspRspImpl (+ mp variants:
            # the fp32 master rows step and cast back).  Rows-only on BOTH
            # sides: an rsp-stored weight/state is gathered and written
            # back through its stored rows, never materialized dense.
            rows = _np.asarray(grad._indices)
            g = grad._values.astype(jnp.float32) * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            if multi_precision:
                mom_state, w32 = state
            else:
                mom_state, w32 = state, weight
            wr = _rows_of(w32, rows).astype(jnp.float32)
            if self.momentum != 0.0 and mom_state is not None:
                mr = _rows_of(mom_state, rows).astype(jnp.float32)
                new_m = self.momentum * mr - lr * (g + wd * wr)
                _write_rows(mom_state, rows, new_m.astype(mom_state.dtype))
                delta = new_m
            else:
                delta = -lr * (g + wd * wr)
            new_rows = wr + delta
            _write_rows(w32, rows, new_rows.astype(w32.dtype))
            if multi_precision:
                _write_rows(weight, rows, new_rows.astype(weight.dtype))
            return
        kw = self._common_kwargs()
        if multi_precision:
            inner, w32 = state
            if self.momentum != 0.0:
                nd.mp_sgd_mom_update(weight, grad, inner, w32, lr=lr, wd=wd,
                                     momentum=self.momentum, **kw)
            else:
                nd.mp_sgd_update(weight, grad, w32, lr=lr, wd=wd, **kw)
        elif state is not None:
            nd.sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                              momentum=self.momentum, **kw)
        else:
            nd.sgd_update(weight, grad, lr=lr, wd=wd, **kw)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and _is_low_prec(weight.dtype)
        self._update_impl(index, weight, grad, state, use_mp)


@register
class ccSGD(SGD):
    """Deprecated alias of SGD (parity: optimizer.py ccSGD — the old
    C++-side SGD; identical math here)."""

@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (parity: optimizer.py NAG — the lookahead
    form: w -= lr*(grad + momentum*mom) after mom = momentum*mom + grad)."""

    fused = True

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.momentum)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        g = self._clip(grad.astype(jnp.float32) * self.rescale_grad) \
            + wd * weight.astype(jnp.float32)
        if self.momentum == 0.0:
            return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype), None
        mom = state.astype(jnp.float32) * self.momentum + g
        neww = weight.astype(jnp.float32) - lr * (g + self.momentum * mom)
        return neww.astype(weight.dtype), mom.astype(state.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            # lazy row-sparse update: only rows present in the gradient
            # step (same invariant as SGD/Adam — untouched rows never
            # decay and their momentum does not advance)
            rows = grad._indices
            g = self._clip(grad._values.astype(jnp.float32)
                           * self.rescale_grad)
            wr = jnp.take(weight._data, rows, axis=0).astype(jnp.float32)
            g = g + wd * wr
            if self.momentum != 0.0 and state is not None:
                mr = jnp.take(state._data, rows, axis=0).astype(jnp.float32)
                new_m = self.momentum * mr + g
                state._set_data(state._data.at[rows].set(
                    new_m.astype(state.dtype)))
                step = lr * (g + self.momentum * new_m)
            else:
                step = lr * g
            weight._set_data(weight._data.at[rows].add(
                (-step).astype(weight.dtype)))
            return
        nw, nmom = self.fused_step(index, weight._data, grad._data,
                                   None if state is None else state._data,
                                   lr, wd, self._index_update_count[index])
        weight._set_data(nw)
        if state is not None:
            state._set_data(nmom)

    def update_multi_precision(self, index, weight, grad, state):
        from .ndarray.sparse import RowSparseNDArray
        if self.multi_precision and _is_low_prec(weight.dtype) \
                and isinstance(grad, RowSparseNDArray):
            # the generic path's grad.astype would densify — recast only
            # the stored values so the lazy row invariant holds under mp
            inner, w32 = state
            g32 = RowSparseNDArray(grad._indices,
                                   grad._values.astype(jnp.float32),
                                   grad.shape, weight.context,
                                   _dedup=False)
            self.update(index, w32, g32, inner)
            w32.copyto(weight)
            return
        super().update_multi_precision(index, weight, grad, state)


@register
class Adam(Optimizer):
    fused = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.beta1, self.beta2,
                self.epsilon)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        from .ops.registry import OP_REGISTRY as _K
        tf = t.astype(jnp.float32)
        coef = jnp.sqrt(1.0 - self.beta2 ** tf) / (1.0 - self.beta1 ** tf)
        p = self._fused_common(lr * coef, wd, beta1=self.beta1,
                               beta2=self.beta2, epsilon=self.epsilon)
        mean, var = state
        nw, nm, nv = _K["adam_update"].fn(p, weight, grad, mean, var)
        return nw, (nm, nv)

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) * math.sqrt(1.0 - self.beta2 ** t) / \
            (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        mean, var = state
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(grad, RowSparseNDArray):
            # lazy row-sparse Adam: only gradient rows step and only their
            # mean/var slots advance (parity: optimizer_op.cc
            # AdamUpdateRspRspRspImpl)
            rows = grad._indices
            g = grad._values.astype(jnp.float32) * self.rescale_grad
            if self.clip_gradient is not None:
                g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
            wr = jnp.take(weight._data, rows, axis=0).astype(jnp.float32)
            g = g + wd * wr
            mr = jnp.take(mean._data, rows, axis=0).astype(jnp.float32)
            vr = jnp.take(var._data, rows, axis=0).astype(jnp.float32)
            nm = self.beta1 * mr + (1 - self.beta1) * g
            nv = self.beta2 * vr + (1 - self.beta2) * jnp.square(g)
            step = lr * nm / (jnp.sqrt(nv) + self.epsilon)
            mean._set_data(mean._data.at[rows].set(nm.astype(mean.dtype)))
            var._set_data(var._data.at[rows].set(nv.astype(var.dtype)))
            weight._set_data(weight._data.at[rows].add(
                (-step).astype(weight.dtype)))
            return
        nd.adam_update(weight, grad, mean, var, lr=lr, wd=wd,
                       beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                       **self._common_kwargs())


@register
class RMSProp(Optimizer):
    fused = True

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.gamma1,
                self.gamma2, self.epsilon, self.centered, self.clip_weights)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        from .ops.registry import OP_REGISTRY as _K
        p = self._fused_common(
            lr, wd, gamma1=self.gamma1, epsilon=self.epsilon,
            clip_weights=self.clip_weights if self.clip_weights else -1.0)
        if self.centered:
            p["gamma2"] = self.gamma2
            n, g, delta = state
            nw, nn, ng, nd_ = _K["rmspropalex_update"].fn(
                p, weight, grad, n, g, delta)
            return nw, (nn, ng, nd_)
        (n,) = state
        nw, nn = _K["rmsprop_update"].fn(p, weight, grad, n)
        return nw, (nn,)

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kwargs()
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta, lr=lr, wd=wd,
                                  gamma1=self.gamma1, gamma2=self.gamma2,
                                  epsilon=self.epsilon, **kw)
        else:
            (n,) = state
            nd.rmsprop_update(weight, grad, n, lr=lr, wd=wd, gamma1=self.gamma1,
                              epsilon=self.epsilon, **kw)


@register
class AdaGrad(Optimizer):
    fused = True

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.float_stable_eps)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        g = self._clip(grad * self.rescale_grad)
        hist = state + g * g
        nw = weight - lr * (g / jnp.sqrt(hist + self.float_stable_eps)
                            + wd * weight)
        return nw, hist

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / (history + self.float_stable_eps).sqrt() + wd * weight)


@register
class AdaDelta(Optimizer):
    fused = True

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.rho, self.epsilon)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        g = self._clip(grad * self.rescale_grad)
        acc_g, acc_delta = state
        nacc_g = self.rho * acc_g + (1.0 - self.rho) * g * g
        cd = jnp.sqrt(acc_delta + self.epsilon) / \
            jnp.sqrt(nacc_g + self.epsilon) * g
        nacc_d = self.rho * acc_delta + (1.0 - self.rho) * cd * cd
        return weight - cd - wd * weight, (nacc_g, nacc_d)

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1.0 - self.rho) * grad * grad
        current_delta = ((acc_delta + self.epsilon).sqrt() /
                         (acc_g + self.epsilon).sqrt()) * grad
        acc_delta[:] = self.rho * acc_delta + (1.0 - self.rho) * \
            current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class Ftrl(Optimizer):
    fused = True

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.lamda1, self.beta)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        from .ops.registry import OP_REGISTRY as _K
        p = self._fused_common(lr, wd, lamda1=self.lamda1, beta=self.beta)
        z, n = state
        nw, nz, nn = _K["ftrl_update"].fn(p, weight, grad, z, n)
        return nw, (nz, nn)

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, lr=self._get_lr(index),
                       wd=self._get_wd(index), lamda1=self.lamda1,
                       beta=self.beta, **self._common_kwargs())


@register
class Adamax(Optimizer):
    fused = True

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def fused_hyper_key(self):
        return (self.rescale_grad, self.clip_gradient, self.beta1, self.beta2)

    def fused_step(self, index, weight, grad, state, lr, wd, t):
        g = self._clip(grad * self.rescale_grad + wd * weight)
        m_t, u_t = state
        nm = self.beta1 * m_t + (1.0 - self.beta1) * g
        nu = jnp.maximum(self.beta2 * u_t, jnp.abs(g))
        lr_t = lr / (1.0 - self.beta1 ** t.astype(jnp.float32))
        return weight - lr_t * nm / nu, (nm, nu)

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index) / (1.0 - self.beta1 ** t)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t[:] = self.beta1 * m_t + (1.0 - self.beta1) * grad
        u_t[:] = nd.maximum(self.beta2 * u_t, grad.abs())
        weight[:] = weight - lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t[:] = self.beta1 * m_t + (1.0 - self.beta1) * grad
        v_t[:] = self.beta2 * v_t + (1.0 - self.beta2) * grad * grad
        grad_prime = grad / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2 ** t)
        m_t_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight[:] = weight - lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (parity: optimizer.py SGLD)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        weight[:] = weight - lr / 2 * (grad + wd * weight) + \
            nd.random.normal(0, math.sqrt(lr), weight.shape,
                             dtype=weight.dtype, ctx=weight.context)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous: Dict[Any, NDArray] = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        comp = grad + wd * weight + self.lamda * grad * grad * \
            (weight - previous_weight)
        if mom is not None:
            mom[:] = self.momentum * mom - lr * comp
            weight[:] = weight + mom
        else:
            weight[:] = weight - lr * comp
        previous_weight[:] = weight


@register
class Test(Optimizer):
    """Simple test optimizer (parity: optimizer.py:1127 — used by kvstore
    server tests)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight[:] = weight + grad * self.rescale_grad
        state[:] = weight


ccSGD = SGD  # deprecated alias kept by the reference


def _conform_state_sharding(state, weight):
    """Place freshly-created optimizer state on the weight's sharding.

    Under a multi-device Module the weights are mesh-replicated
    (NamedSharding); states created by nd.zeros land on one device and
    would make the fused update's jit see mixed placements.  Same-shape
    leaves (momentum, fp32 masters) take the weight's own sharding;
    other array leaves replicate over the weight's mesh."""
    from .ndarray.sparse import BaseSparseNDArray
    if isinstance(weight, BaseSparseNDArray):
        # rows-only storage is host-orchestrated; no mesh sharding to
        # conform to (and ._data would materialize the dense O(vocab) view)
        return state
    wdata = weight._data if isinstance(weight, NDArray) else weight
    sharding = getattr(wdata, "sharding", None)
    if sharding is None or not hasattr(sharding, "mesh") or \
            len(getattr(wdata, "devices", lambda: [0])()) <= 1:
        return state

    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(sharding.mesh, PartitionSpec())

    def place(s):
        if s is None:
            return None
        if isinstance(s, NDArray):
            tgt = sharding if s.shape == wdata.shape else repl
            s._set_data(jax.device_put(s._data, tgt))
            return s
        if isinstance(s, (tuple, list)):
            return type(s)(place(x) for x in s)
        return s

    return place(state)


def _register_state(state) -> None:
    """Ledger-register raw jax arrays inside an optimizer state tree
    (NDArray states already self-registered at creation under the
    enclosing memory_scope)."""
    if state is None or isinstance(state, NDArray):
        return
    if isinstance(state, (tuple, list)):
        for s in state:
            _register_state(s)
        return
    if hasattr(state, "shape") and hasattr(state, "dtype"):
        _memory.register(state, tag="optimizer_state")


class Updater:
    """Applies an optimizer with per-index states (parity: optimizer.get_updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states: Dict[Any, Any] = {}
        self.states_synced: Dict[Any, bool] = {}

    def _ensure_state(self, index, weight):
        if index not in self.states:
            # HBM ledger: optimizer state (momentum/adam moments, fp32
            # masters) is born here — NDArray states self-register under
            # the scope tag, raw jax states register explicitly
            with _memory.memory_scope("optimizer_state"):
                state = self.optimizer.create_state_multi_precision(
                    index, weight)
                state = _conform_state_sharding(state, weight)
                if _memory.ENABLED:
                    _register_state(state)
            self.states[index] = state
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index],
                                                         weight.context)
            self.states_synced[index] = True

    def __call__(self, index, grad, weight):
        self._ensure_state(index, weight)
        if _metrics.ENABLED:
            _metrics.OPTIMIZER_STEPS.inc()
            # a per-key update launches at least one device program; the
            # legacy (non-fused) trainer path is O(params) of these, and
            # TRAINER_STEP_DISPATCHES must show that against the fused
            # path's single update_all launch
            _metrics.XLA_LAUNCHES.inc(kind="optimizer")
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        if dump_optimizer:
            return pickle.dumps((
                {k: _to_np_state(v) for k, v in self.states.items()},
                self.optimizer))
        return pickle.dumps({k: _to_np_state(v) for k, v in self.states.items()})


def _to_np_state(state):
    # states pickle as numpy; rehydrated lazily on first use
    if isinstance(state, NDArray):
        return state
    return state


class HyperDeviceCache:
    """Device-cached (lr, wd) vectors + a device-resident step counter
    per key tuple — the ONE implementation behind
    ``FusedUpdater.hyper_arrays`` and ``WholeStepCompiler``'s hyper
    plumbing (formerly two ~30-line mirrors; the fused/whole-step
    bitwise-parity tests pin that sharing it changes nothing).

    Through the tunnel every fresh host->device transfer costs a
    latency hop on the hot path, so lr/wd re-upload only when a
    schedule actually changes them (last-VALUE cache — a per-step
    schedule must not grow a dict by one device array per step), and
    the step counter lives ON DEVICE, incremented by the compiled
    update itself; call ``commit(...)`` after the step lands.  When the
    python-side schedule counts diverge from the committed device
    counter (a per-key update interleaved, ``load_states``), the
    counter re-seeds from them — or, via ``pending_ts``, from a
    checkpointed APPLIED-step vector (fp16 skip-steps make Adam's
    bias-correction t lag the schedule counts; docs/perf_tuning.md)."""

    def __init__(self):
        self._hc: Dict[str, Any] = {}
        self._ts: Dict[tuple, tuple] = {}  # idx -> (device ts, counts)

    def arrays(self, opt_, indices, pending_ts=None):
        """Return ``(lrs, wds, ts, counts_t)`` for ``indices``.
        ``pending_ts``: zero-arg callable yielding an int tuple to seed
        the device counter from (consumed only when a (re)seed actually
        happens), or None."""
        idx = tuple(indices)
        hc = self._hc
        lr_t = tuple(opt_._get_lr(i) for i in idx)
        wd_t = tuple(opt_._get_wd(i) for i in idx)
        # np.array over PYTHON scalars (lr/wd schedules) builds a host
        # constant to ship device-ward — no device value is read, so
        # these are not the syncs the host-sync rule hunts:
        if hc.get("lr_key") != lr_t:
            hc["lr_key"] = lr_t
            hc["lr"] = jnp.asarray(_np.array(lr_t, _np.float32))  # graft-lint: disable=host-sync
        if hc.get("wd_key") != wd_t:
            hc["wd_key"] = wd_t
            hc["wd"] = jnp.asarray(_np.array(wd_t, _np.float32))  # graft-lint: disable=host-sync
        counts_t = tuple(opt_._index_update_count[i] for i in idx)
        ent = self._ts.get(idx)
        if ent is not None and ent[1] == counts_t:
            ts = ent[0]
        else:
            seed = pending_ts() if pending_ts is not None else None
            # python ints -> device constant (see lr/wd note above)
            ts = jnp.asarray(_np.array(
                counts_t if seed is None else seed, _np.int32))  # graft-lint: disable=host-sync
        return hc["lr"], hc["wd"], ts, counts_t

    def commit(self, indices, new_ts, counts_t) -> None:
        """Adopt the stepped device counter for ``indices`` — valid
        while the python schedule counts advance exactly once."""
        self._ts[tuple(indices)] = (new_ts,
                                    tuple(c + 1 for c in counts_t))


class FusedUpdater(Updater):
    """Multi-tensor updater: ONE jitted XLA program updates every parameter.

    The TPU redesign of the reference's per-parameter engine pushes
    (python/mxnet/model.py:126 `_update_params_on_kvstore` loops keys; the
    engine bulks op segments, graph_executor.cc:1350).  Here the whole
    grads→optimizer→params pass for all keys traces into a single compiled
    call per step: Module.update / Trainer.step / KVStore.pushpull issue O(1)
    dispatches regardless of parameter count.  Per-key `__call__` (inherited)
    stays available and bit-identical for optimizers without a fused_step.
    """

    #: compiled-step program cache bound (LRU).  Generous: a training
    #: process legitimately holds a handful of live programs (per step
    #: mode x dtype policy x param-group signature); what must NOT
    #: accumulate are dead entries from recreated whole-step compilers
    FN_CACHE_MAX = 64

    def __init__(self, optimizer: Optimizer):
        super().__init__(optimizer)
        self._fn_cache: Dict[Any, Any] = {}
        # introspection captures done, one per compiled-step cache key
        self._noted_keys: set = set()
        # dtype policy the compiled step programs were traced under
        # ("f32" | "bf16" | "fp16"; set from MXNET_AMP by the trainer /
        # whole-step compiler).  It is position 1 of every program cache
        # key, so a policy flip can never silently reuse a program traced
        # for another precision — see lookup_program.
        self.dtype_policy = "f32"

    def lookup_program(self, key, build):
        """Compiled-step program cache shared by update_all and the gluon
        whole-step compiler (`gluon/wholestep.py`).

        ``key`` = (step_mode, dtype_policy, *rest): step_mode names the
        program shape ("update_all" / "whole_step"), dtype_policy the
        MXNET_AMP precision it was traced under.  A miss whose ``rest``
        matches a cached entry under a DIFFERENT dtype policy recompiles
        LOUDLY — warning + FUSED_DTYPE_RECOMPILES counter — because the
        silent failure mode here is real: reusing an f32-traced program
        for bf16/fp16 gradients would train in the wrong precision
        without ever erroring."""
        fn = self._fn_cache.get(key)
        if fn is not None:
            self._fn_cache[key] = self._fn_cache.pop(key)  # LRU refresh
            return fn
        for k2 in self._fn_cache:
            if isinstance(k2, tuple) and len(k2) >= 2 and \
                    k2[0] == key[0] and k2[1] != key[1] and \
                    k2[2:] == key[2:]:
                _logger.warning(
                    "dtype-policy change (%s -> %s): recompiling the %s "
                    "fused program — the %s-traced program is NOT reused",
                    k2[1], key[1], key[0], k2[1])
                if _metrics.ENABLED:
                    # key[0] comes from the two call sites' literals
                    # ("update_all" / "whole_step") — bounded label set
                    _metrics.FUSED_DTYPE_RECOMPILES.inc(mode=key[0])
                break
        fn = build()
        self._fn_cache[key] = fn
        # bounded LRU: superseded programs (dead per-compiler uids,
        # abandoned dtype policies) must not pin their jitted
        # executables + traced-graph closures for the trainer's
        # lifetime; evicting a LIVE entry only costs a retrace
        while len(self._fn_cache) > self.FN_CACHE_MAX:
            evicted = next(iter(self._fn_cache))
            del self._fn_cache[evicted]
            _logger.info("fused program cache full (%d): evicted LRU "
                         "entry %s/%s", self.FN_CACHE_MAX,
                         evicted[0], evicted[1])
        return fn

    @staticmethod
    def _state_data(state):
        if state is None:
            return None
        if isinstance(state, NDArray):
            return state._data
        if isinstance(state, (tuple, list)):
            return tuple(FusedUpdater._state_data(s) for s in state)
        return state

    def _state_writeback(self, old, new):
        if old is None:
            return None
        if isinstance(old, NDArray):
            old._set_data(new)
            return old
        if isinstance(old, (tuple, list)):
            return type(old)(self._state_writeback(o, n)
                             for o, n in zip(old, new))
        # raw jax state: the registered old array dies here — the
        # replacement must re-register or optimizer_state attribution
        # drifts to zero after the first fused step (same per-step
        # re-registration the compression residuals do)
        if _memory.ENABLED:
            _memory.register(new, tag="optimizer_state")
        return new

    def hyper_arrays(self, indices):
        """Device-cached (lrs, wds, ts, commit_ts) for a key tuple —
        ``HyperDeviceCache`` does the work (one implementation shared
        with ``WholeStepCompiler``, so fused/whole-step optimizer state
        stays interchangeable by construction).  Shared by update_all
        and the module-level fused train step."""
        # lazy but allocation-free once built: setdefault would
        # construct (and discard) a fresh cache object every step
        cache = self.__dict__.get("_hyper_dev")
        if cache is None:
            cache = self.__dict__["_hyper_dev"] = HyperDeviceCache()
        idx = tuple(indices)
        lrs, wds, ts, counts_t = cache.arrays(self.optimizer, idx)

        def commit_ts(nts):
            cache.commit(idx, nts, counts_t)

        return lrs, wds, ts, commit_ts

    @staticmethod
    def _materialize_views(grads, grad_views):
        """Slice per-key gradients out of flat bucket arrays eagerly (the
        rare non-fused-optimizer fallback; the fused path slices inside
        its compiled program instead)."""
        out = []
        for b, off, shape in grad_views:
            f = grads[b]._data if isinstance(grads[b], NDArray) else grads[b]
            size = int(_np.prod(shape)) if shape else 1
            out.append(f[off:off + size].reshape(shape))
        return out

    @hot_path
    def update_all(self, indices, grads, weights, grad_views=None,
                   donate_weights=None) -> None:
        """Apply the optimizer to all (grad, weight) pairs in one dispatch.

        grads: NDArray or raw jax arrays; weights: NDArrays (updated
        in place via _set_data).  Falls back to the per-key path for
        optimizers without fused_step.

        grad_views: when set, `grads` holds the FLAT BUCKET arrays of a
        bucketed allreduce (kvstore.GradBucketer) and grad_views[k] =
        (bucket, offset, shape) locates parameter k's gradient inside
        them; the slice+reshape traces into the same fused program, so
        un-flattening costs no extra dispatch or copy.  (The bucket
        buffers are NOT donated — no output shares their shape — they
        stay live until the trainer drops its reference after the call.)

        2-bit-compressed buckets arrive here already dequantized in the
        gradient dtype (the error-feedback residual treedef lives with
        the Trainer/kvstore, never in this program), so the cache key
        below is compression-agnostic by construction: toggling
        compression_params cannot grow the compiled-step cache.

        donate_weights (default MXNET_DONATE_WEIGHTS, off): donate the
        weight buffers too — each new weight aliases its old buffer, so
        the optimizer step updates parameters truly IN PLACE (no second
        copy of the model live during the update).  Off by default
        because executor snapshots / user-held NDArray views may still
        alias the old buffers; enable when the trainer owns the weights
        outright (docs/perf_tuning.md).
        """
        opt_ = self.optimizer
        if donate_weights is None:
            donate_weights = getenv("MXNET_DONATE_WEIGHTS", False)
        if not getattr(opt_, "fused", False):
            if grad_views is not None:
                grads = self._materialize_views(grads, grad_views)
            for i, g, w in zip(indices, grads, weights):
                g = g if isinstance(g, NDArray) else NDArray(g, w.context)
                self(i, g, w)
            return
        from .ndarray.sparse import RowSparseNDArray
        if grad_views is None and \
                any(isinstance(g, RowSparseNDArray) for g in grads):
            # rsp grads take the FUSED sparse leg (ISSUE 20): rows-only
            # gather/step/scatter in one compiled program (reading ._data
            # here would densify the O(vocab) gradient the executor just
            # kept rows-only); dense keys stay in the multi-tensor trace
            sparse = [(i, g, w) for i, g, w in zip(indices, grads, weights)
                      if isinstance(g, RowSparseNDArray)]
            dense = [(i, g, w) for i, g, w in zip(indices, grads, weights)
                     if not isinstance(g, RowSparseNDArray)]
            si, sg, sw = zip(*sparse)
            self.update_sparse(list(si), list(sg), list(sw),
                               donate_weights=donate_weights)
            if dense:
                di, dg, dw = zip(*dense)
                self.update_all(list(di), list(dg), list(dw),
                                donate_weights=donate_weights)
            return
        indices = list(indices)
        for i, w in zip(indices, weights):
            self._ensure_state(i, w)
        for i in indices:
            opt_._update_count(i)
        lrs, wds, ts, commit_ts = self.hyper_arrays(indices)
        wvals = [w._data for w in weights]
        gvals = [g._data if isinstance(g, NDArray) else g for g in grads]
        svals = [self._state_data(self.states[i]) for i in indices]
        views = tuple(grad_views) if grad_views is not None else None

        # dispatch-stability key: identity of the compiled step is pinned
        # on (step mode, dtype policy, optimizer, hypers, key tuple,
        # dtypes, shardings, state treedef, bucket views) — any drift
        # re-selects a cached program instead of silently retracing under
        # the same entry, and a dtype-policy flip recompiles loudly
        # (lookup_program)
        key = ("update_all", self.dtype_policy,
               type(opt_).__name__, opt_.fused_hyper_key(), tuple(indices),
               tuple(str(w.dtype) for w in wvals),
               tuple(str(g.dtype) for g in gvals),
               tuple(str(getattr(w, "sharding", None)) for w in wvals),
               jax.tree_util.tree_structure(svals), views,
               bool(donate_weights))

        def _build():
            idx = list(indices)

            def _apply(wv, gv, sv, lrs, wds, ts):
                # the fused optimizer math traces under one literal
                # named scope, so per_layer() attributes its HLO
                # instructions to "optimizer" (ISSUE 13)
                with _introspect.layer_scope("optimizer"):
                    nws, nss = [], []
                    for k in range(len(wv)):
                        if views is not None:
                            b, off, shape = views[k]
                            size = int(_np.prod(shape)) if shape else 1
                            g_k = gv[b][off:off + size].reshape(shape)
                        else:
                            g_k = gv[k]
                        nw, ns = opt_._fused_step_mp(idx[k], wv[k], g_k,
                                                     sv[k], lrs[k], wds[k],
                                                     ts[k])
                        nws.append(cast_like(nw, wv[k]))
                        nss.append(cast_like(ns, sv[k]))
                    return nws, nss, ts + 1

            # donate states (owned exclusively by this updater, aliased to
            # the new-state outputs); weights join the donation set only
            # under the donate_weights knob — executor snapshots may
            # still alias their buffers in the general case.  Flat grad
            # buckets are NOT donated: no output shares their shape, so
            # donation could never alias and would only warn.
            return jax.jit(_apply,
                           donate_argnums=(0, 2) if donate_weights else (2,))

        fn = self.lookup_program(key, _build)
        if _introspect.ENABLED and key not in self._noted_keys:
            # once per compiled-step cache key, BEFORE the call (the
            # donated state buffers are still live): analytical cost of
            # the fused update — a retrace, no XLA compile, no dispatch.
            # The signature hashes the dispatch-stability key (optimizer
            # class, hypers, param set, dtypes, shardings, state
            # treedef), so perf baselines stay per-(model, optimizer,
            # platform) — two different models must never share one
            # baseline file
            self._noted_keys.add(key)
            import hashlib
            sig = hashlib.sha1(repr(key).encode()).hexdigest()[:16]
            # auditable program contract (analysis.audit_programs,
            # ISSUE 15): donated state (and weight, under
            # donate_weights) leaves must alias outputs; the fused
            # update is pure optimizer math — no host callbacks, no
            # collectives (the bucketed allreduce runs in its own
            # program on this path)
            donated = (0, 2) if donate_weights else (2,)
            leaves = len(jax.tree_util.tree_leaves(svals)) + \
                (len(jax.tree_util.tree_leaves(wvals)) if donate_weights
                 else 0)
            _introspect.note_jit("fused_update", fn, wvals, gvals, svals,
                                 lrs, wds, ts, signature=sig,
                                 contracts={"donate_argnums": donated,
                                            "donated_leaves": leaves,
                                            "host_callbacks": 0,
                                            "collectives": 0})
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="optimizer")
            _metrics.OPTIMIZER_STEPS.inc()
        # OOM post-mortem chokepoint: the fused multi-tensor update is
        # the other program that holds a whole model (+states) live;
        # the memory.oom chaos site injects a synthetic one here
        with trace_span("optimizer_update_all", cat="optimizer"), \
                _memory.oom_guard("optimizer.update_all"):
            _fi_fire("memory.oom", at="optimizer")
            # transient-device chaos site at the fused-update dispatch
            # boundary (the fused-path twin of the whole-step site):
            # fires before fn(), so weights/states are still pre-step
            _fi_fire("device.unavailable", at="optimizer")
            try:
                nws, nss, nts = fn(wvals, gvals, svals, lrs, wds, ts)
            except BaseException:
                # MXNET_SANITIZE twin (ISSUE 15): the failed donated
                # dispatch may have consumed the state (and, under
                # donate_weights, weight) buffers — poison the
                # wrappers so later touches raise typed
                # DonatedBufferError; set_states_bytes / _set_data on
                # restore clears the poison
                if _san_mod.ENABLED:
                    _san_mod.poison_donated(
                        "fused_update",
                        *[self.states[i] for i in indices],
                        *(list(weights) if donate_weights else []))
                raise
        commit_ts(nts)
        for k, i in enumerate(indices):
            weights[k]._set_data(nws[k])
            self.states[i] = self._state_writeback(self.states[i], nss[k])

    def _rowable_state(self, state, vocab) -> bool:
        """True when every state leaf is a DENSE per-row slab (leading dim
        == vocab) the sparse leg can gather/scatter by row — rsp-stored
        or scalar/oddly-shaped state exiles that key to the per-key lazy
        path instead of silently densifying."""
        if state is None:
            return True
        if isinstance(state, (tuple, list)):
            return all(self._rowable_state(s, vocab) for s in state)
        if getattr(state, "stype", "default") != "default":
            return False
        shp = getattr(state, "shape", None)
        return bool(shp) and shp[0] == vocab

    @hot_path
    def update_sparse(self, indices, grads, weights,
                      donate_weights=None) -> None:
        """Fused ROW-SPARSE optimizer leg (ISSUE 20): one compiled
        program steps every row-sparse (grad, weight) pair — gather the
        touched weight/state rows, run the optimizer's ``fused_step`` on
        the O(nnz) row slabs, scatter back with ``.at[ids].set(...,
        mode="drop")``.  Replaces the per-key exile that cost one python
        round-trip + several dispatches PER EMBEDDING per step.

        Semantics match the eager lazy-update paths bit-for-bit in
        structure: only gradient rows step (their wd term included),
        only their optimizer-state slots advance, per-key t (not
        per-row) feeds Adam's bias correction.

        grads: RowSparseNDArrays (sorted-unique ids by construction;
        ``MXNET_EMBED_DEDUP_IDS=0`` wire duplicates are legal — the
        program always runs its own unique + segment-sum, a bitwise
        identity on already-unique input).  nnz is padded OUTSIDE the
        jit to the next power of two with a POSITIVELY out-of-range
        sentinel id (vocab — never -1, which ``.at[]`` would wrap onto
        the last real row), so steady-state traffic reuses log-many
        compiled programs instead of one per nnz.

        Optimizers without ``fused_step``, rsp-STORED weights, and
        non-row-gatherable state (rsp momentum, scalar accumulators)
        exile per-key exactly as before — rows-only either way."""
        opt_ = self.optimizer
        if donate_weights is None:
            donate_weights = getenv("MXNET_DONATE_WEIGHTS", False)
        from .ndarray.sparse import RowSparseNDArray
        for g in grads:
            if not isinstance(g, RowSparseNDArray):
                raise TypeError("update_sparse expects row_sparse grads, "
                                f"got {type(g).__name__}")
        for i, w in zip(indices, weights):
            self._ensure_state(i, w)
        fused, exiled = [], []
        for i, g, w in zip(indices, grads, weights):
            ok = getattr(opt_, "fused", False) and \
                getattr(w, "stype", "default") == "default" and \
                self._rowable_state(self.states[i], w.shape[0])
            (fused if ok else exiled).append((i, g, w))
        for i, g, w in exiled:
            self(i, g, w)
        if not fused:
            return
        indices = [i for i, _, _ in fused]
        grads = [g for _, g, _ in fused]
        weights = [w for _, _, w in fused]
        for i in indices:
            opt_._update_count(i)
        lrs, wds, ts, commit_ts = self.hyper_arrays(indices)
        wvals = [w._data for w in weights]
        svals = [self._state_data(self.states[i]) for i in indices]
        # pad ids/rows OUTSIDE the jit to the pow2 nnz bucket; sentinel
        # = vocab is dropped by every mode="drop" scatter below (and the
        # matching mode="clip" gathers read a real row whose update is
        # then dropped — garbage-in, dropped-out)
        ivals, gvals, buckets = [], [], []
        for g, w in zip(grads, weights):
            nnz = int(g._indices.shape[0])
            bucket = max(8, 1 << max(0, nnz - 1).bit_length())
            sent = w.shape[0]
            ids = jnp.full((bucket,), sent, g._indices.dtype) \
                .at[:nnz].set(g._indices)
            rows = jnp.zeros((bucket,) + g._values.shape[1:],
                             g._values.dtype).at[:nnz].set(g._values)
            ivals.append(ids)
            gvals.append(rows)
            buckets.append(bucket)

        key = ("sparse_update", self.dtype_policy,
               type(opt_).__name__, opt_.fused_hyper_key(), tuple(indices),
               tuple(str(w.dtype) for w in wvals),
               tuple(str(g.dtype) for g in gvals), tuple(buckets),
               tuple(str(getattr(w, "sharding", None)) for w in wvals),
               jax.tree_util.tree_structure(svals), bool(donate_weights))

        def _build():
            idx = list(indices)

            def _apply(wv, iv, gv, sv, lrs, wds, ts):
                with _introspect.layer_scope("optimizer"):
                    nws, nss = [], []
                    for k in range(len(wv)):
                        vocab = wv[k].shape[0]
                        # in-program dedup: segment-sum duplicate ids
                        # exactly once (identity on the default
                        # already-unique wire); sentinel slots collapse
                        # onto the fill entry and scatter-drop
                        uids, inv = jnp.unique(
                            iv[k], size=iv[k].shape[0], fill_value=vocab,
                            return_inverse=True)
                        g_k = jnp.zeros(gv[k].shape, gv[k].dtype) \
                            .at[jnp.ravel(inv)].add(gv[k])
                        wr = jnp.take(wv[k], uids, axis=0, mode="clip")
                        sr = jax.tree_util.tree_map(
                            lambda s: jnp.take(s, uids, axis=0,
                                               mode="clip"), sv[k])
                        nwr, nsr = opt_._fused_step_mp(
                            idx[k], wr, g_k, sr, lrs[k], wds[k], ts[k])
                        nws.append(wv[k].at[uids].set(
                            cast_like(nwr, wr), mode="drop"))
                        nss.append(jax.tree_util.tree_map(
                            lambda s, r: s.at[uids].set(cast_like(r, s),
                                                        mode="drop"),
                            sv[k], nsr))
                    return nws, nss, ts + 1

            # states are owned by this updater — donated, and the
            # row-scatter output is table-shaped so donation really
            # aliases; weights join only under donate_weights (same
            # caveat as update_all: user-held views may alias them).
            # The padded id/row slabs are NOT donated (wrong shapes).
            return jax.jit(_apply,
                           donate_argnums=(0, 3) if donate_weights else (3,))

        fn = self.lookup_program(key, _build)
        if _introspect.ENABLED and key not in self._noted_keys:
            self._noted_keys.add(key)
            import hashlib
            sig = hashlib.sha1(repr(key).encode()).hexdigest()[:16]
            donated = (0, 3) if donate_weights else (3,)
            leaves = len(jax.tree_util.tree_leaves(svals)) + \
                (len(jax.tree_util.tree_leaves(wvals)) if donate_weights
                 else 0)
            _introspect.note_jit("sparse_update", fn, wvals, ivals, gvals,
                                 svals, lrs, wds, ts, signature=sig,
                                 contracts={"donate_argnums": donated,
                                            "donated_leaves": leaves,
                                            "host_callbacks": 0,
                                            "collectives": 0})
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="optimizer")
            _metrics.OPTIMIZER_STEPS.inc()
        with trace_span("optimizer_update_sparse", cat="optimizer"), \
                _memory.oom_guard("optimizer.update_sparse"):
            _fi_fire("memory.oom", at="optimizer")
            _fi_fire("device.unavailable", at="optimizer")
            try:
                nws, nss, nts = fn(wvals, ivals, gvals, svals, lrs, wds, ts)
            except BaseException:
                if _san_mod.ENABLED:
                    _san_mod.poison_donated(
                        "sparse_update",
                        *[self.states[i] for i in indices],
                        *(list(weights) if donate_weights else []))
                raise
        commit_ts(nts)
        for k, i in enumerate(indices):
            weights[k]._set_data(nws[k])
            self.states[i] = self._state_writeback(self.states[i], nss[k])


def get_updater(optimizer: Optimizer) -> Updater:
    return FusedUpdater(optimizer)


# NDArray needs nd.maximum for Adamax — ensure generated fn exists
