"""Training-side fault taxonomy: typed errors, failure classification,
post-mortem dumps (ISSUE 12).

PR 6 gave *serving* a resilience tier; this module is the shared
vocabulary the *training* twin builds on.  Production training dies in
three distinct ways, and the right reaction differs per class:

  ==============  =========================================================
  **transient**   The device/RPC layer hiccuped (UNAVAILABLE tunnel, RPC
                  deadline, preempted DMA, injected chaos).  The step is
                  re-executable: the ``TrainingSupervisor`` restores its
                  rolling host snapshot and replays — the MXNet paper's
                  KVStore-as-recovery-consistency-point (arxiv
                  1512.01274), jax-native.
  **oom**         Device memory is gone (``DeviceMemoryError`` /
                  ``HBMBudgetError`` from the PR 9 ledger).  Retrying the
                  identical program re-OOMs; propagate with the
                  post-mortem attached.
  **permanent**   A trace/user error (shape bug, ineligible op, NaN in
                  user code).  Retrying cannot help; propagate
                  immediately.
  ==============  =========================================================

``classify(exc)`` maps an exception to one of these three strings;
``post_mortem(reason, ...)`` writes the rate-limited black-box report
(flight ring + HBM ledger, the PR 8/9 surfaces) the watchdogs attach to
their typed errors.  The typed errors live here — not in
``gluon/supervisor.py`` — because the data pipeline
(``gluon/data/prefetcher.py``, ``io.PrefetchingIter``) and the fault
injector need them without importing gluon.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, Optional

from .base import MXNetError, atomic_write, unique_path

log = logging.getLogger(__name__)

__all__ = ["TRANSIENT", "OOM", "PERMANENT", "classify",
           "DeviceUnavailableError", "DivergenceError",
           "TrainingStalledError", "StepRetriesExhausted",
           "DataCorruptionError", "DataSkipBudgetError",
           "post_mortem", "last_post_mortem", "reset"]

#: classification buckets ``classify`` returns
TRANSIENT = "transient"
OOM = "oom"
PERMANENT = "permanent"


# ---------------------------------------------------------------------------
# typed errors
# ---------------------------------------------------------------------------
class DeviceUnavailableError(MXNetError):
    """The accelerator (or its RPC tunnel) reported UNAVAILABLE — the
    transient device-loss class (also what the ``device.unavailable``
    faultinject site raises).  Always classified transient."""


class DivergenceError(MXNetError):
    """The divergence watchdog tripped: ``MXNET_SUPERVISE_DIVERGE_PATIENCE``
    consecutive nonfinite losses.  Carries ``step`` and the post-mortem
    paths in ``report``."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 report: Optional[dict] = None):
        super().__init__(msg)
        self.step = step
        self.report = report or {}


class TrainingStalledError(MXNetError):
    """The stall watchdog tripped: a step exceeded its EWMA-derived
    deadline and the device is presumed wedged.  Carries ``step``,
    ``timeout_s``, and the post-mortem paths in ``report``."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 report: Optional[dict] = None):
        super().__init__(msg)
        self.step = step
        self.timeout_s = timeout_s
        self.report = report or {}


class StepRetriesExhausted(MXNetError):
    """A transient step failure survived every donation-safe retry
    (``MXNET_SUPERVISE_RETRIES``).  ``__cause__`` chains the last
    underlying transient error."""

    def __init__(self, msg: str, step: Optional[int] = None):
        super().__init__(msg)
        self.step = step


class DataCorruptionError(MXNetError):
    """One input record could not be decoded (bit-rot, truncated
    download, bad serialization).  The prefetcher's skip budget
    (``MXNET_DATA_SKIP_BUDGET``) consumes these instead of killing the
    epoch; raise it from custom datasets/decoders to opt in."""


class DataSkipBudgetError(MXNetError):
    """The corrupt-record skip budget is exhausted — the input data is
    damaged beyond the configured tolerance, which is an operator
    problem, not a record problem."""


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------
# substrings that mark a device/RPC error as transient when the type
# alone can't (jaxlib surfaces gRPC status phrases inside
# XlaRuntimeError strings)
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                     "CANCELLED", "connection reset", "socket closed",
                     "failed to connect")


def classify(exc: BaseException) -> str:
    """Map a step failure to ``TRANSIENT`` / ``OOM`` / ``PERMANENT``.

    Rules (first match wins):

    * ``DeviceMemoryError`` / ``HBMBudgetError`` → ``oom`` — the typed
      re-raise ``memory.oom_guard`` produces after its own post-mortem.
    * ``DeviceUnavailableError``, ``faultinject.InjectedFault``,
      ``OSError``/``IOError``/``ConnectionError``/``TimeoutError`` →
      ``transient``.  (Note: the *checkpoint* retry loop deliberately
      treats ``InjectedFault`` as non-retryable to exercise retry
      exhaustion; the supervisor taxonomy classifies it transient so
      ``MXNET_FAULT_PLAN`` raise rules model recoverable device faults.)
    * Any exception whose text carries a gRPC-transient status phrase
      (UNAVAILABLE, DEADLINE_EXCEEDED, ...) → ``transient`` — how a
      jaxlib ``XlaRuntimeError`` from a dropped TPU tunnel classifies.
    * Everything else → ``permanent`` (trace/user errors: retrying the
      same program on the same data cannot succeed).
    """
    from .observability.memory import DeviceMemoryError, HBMBudgetError
    if isinstance(exc, (DeviceMemoryError, HBMBudgetError)):
        return OOM
    if isinstance(exc, (DataCorruptionError, DataSkipBudgetError)):
        # damaged *data* is not a retryable *device* condition: replaying
        # the same record re-fails, so the prefetcher's skip budget — not
        # the supervisor's snapshot retry — is the handler
        return PERMANENT
    if isinstance(exc, DeviceUnavailableError):
        return TRANSIENT
    from .faultinject import InjectedFault
    if isinstance(exc, InjectedFault):
        return TRANSIENT
    if isinstance(exc, (OSError, ConnectionError, TimeoutError)):
        return TRANSIENT
    text = f"{type(exc).__name__}: {exc}"
    if any(m in text for m in _TRANSIENT_MARKERS):
        return TRANSIENT
    return PERMANENT


# ---------------------------------------------------------------------------
# post-mortem dumps
# ---------------------------------------------------------------------------
#: minimum seconds between post-mortem dumps per reason (tests set 0) —
#: the same never-spam-the-disk posture as flight.AUTO_DUMP_MIN_S /
#: memory.OOM_DUMP_MIN_S
POST_MORTEM_MIN_S = 30.0

_pm_lock = threading.Lock()
_last_pm_t: Dict[str, float] = {}
_last_pm: Dict[str, dict] = {}


def post_mortem(reason: str, step: Optional[int] = None,
                detail: Optional[dict] = None) -> Optional[dict]:
    """Write the training black-box report for ``reason`` ("divergence",
    "stall", "preempt", ...): one JSON post-mortem (failing step id,
    caller detail, HBM ledger report, watchdog EWMAs) plus a flight-ring
    timeline dump, both under ``MXNET_FLIGHT_DIR``.  Rate-limited per
    reason by ``POST_MORTEM_MIN_S`` — a watchdog that keeps tripping
    produces exactly one dump per window, never a disk flood.  Returns
    ``{"report_path", "flight_path", ...}`` or ``None`` when
    rate-limited.  Runs inline (the callers are about to raise a typed
    error or rewind — not a hot path), and never raises itself."""
    now = time.monotonic()
    with _pm_lock:
        t = _last_pm_t.get(reason)
        if t is not None and now - t < POST_MORTEM_MIN_S:
            return None
        _last_pm_t[reason] = now
    info: dict = {"reason": reason, "step": step, "time": time.time()}
    if detail:
        info["detail"] = dict(detail)
    from .observability import flight as _flight
    from .observability import journal as _journal
    from .observability import memory as _memory
    if _journal.ENABLED:
        # cross-reference both ways: the report names its run + journal
        # and the journal names the report files (ISSUE 16 satellite)
        info["run_id"] = _journal.run_id()
        info["journal_path"] = _journal.path()
    try:
        payload = dict(info)
        if _memory.ENABLED:
            payload["memory"] = _memory.report()
        payload["watch"] = _flight.watch_state()
        d = os.environ.get("MXNET_FLIGHT_DIR", ".") or "."
        os.makedirs(d, exist_ok=True)
        path = unique_path(d, f"postmortem-{reason}", ".json")
        atomic_write(path, json.dumps(payload, default=str))
        info["report_path"] = path
    except Exception as e:  # noqa: BLE001 — a failed dump must not mask
        log.warning("post-mortem report (%s) failed: %s", reason, e)
        info["report_path"] = None
    try:
        info["flight_path"] = _flight.dump(reason=reason) \
            if _flight.ENABLED else None
    except Exception as e:  # noqa: BLE001
        log.warning("post-mortem flight dump (%s) failed: %s", reason, e)
        info["flight_path"] = None
    log.warning("post-mortem (%s) at step %s: report=%s flight=%s",
                reason, step, info.get("report_path"),
                info.get("flight_path"))
    if _journal.ENABLED:
        _journal.emit("post_mortem", step=step, durable=True,
                      why=reason,
                      report_path=info.get("report_path"),
                      flight_path=info.get("flight_path"))
    with _pm_lock:
        _last_pm[reason] = info
    return info


def last_post_mortem(reason: str) -> Optional[dict]:
    """The most recent ``post_mortem`` result for ``reason`` (tests and
    operators; None when none fired)."""
    with _pm_lock:
        return dict(_last_pm[reason]) if reason in _last_pm else None


def reset() -> None:
    """Drop rate-limit windows and recorded post-mortems (tests)."""
    with _pm_lock:
        _last_pm_t.clear()
        _last_pm.clear()
