"""Framework-global PRNG key stream (parity: python/mxnet/random.py + the
per-device ResourceManager kRandom resource, src/resource.cc:85-147).

Functional JAX keys replace stateful per-device generators: `seed(n)` resets
the root key; every eager random op consumes one split.  Graph executors fold
a per-run key by node id instead (trace-safe).
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


# host-side RandomState for initializers (the reference's initializers
# draw from the engine RNG that mx.random.seed controls; ours draw host-
# side, so the framework owns its own stream — never numpy's global one)
import numpy as _np
host_rng = _np.random.RandomState(0)


def seed(seed_state: int) -> None:
    """Seed the framework RNG (parity: mx.random.seed / MXRandomSeed) —
    both the jax key stream and the host RNG that initializers use."""
    _state.key = jax.random.PRNGKey(int(seed_state))
    host_rng.seed(int(seed_state) % (2 ** 32))


def next_key():
    key = _get()
    _state.key, sub = jax.random.split(key)
    return sub


# nd-level sampling functions are attached in ndarray.random (autogen);
# keep module-level aliases for mx.random.uniform(...) etc.
def __getattr__(name):
    from . import ndarray
    fn = getattr(ndarray.random, name, None)
    if fn is None:
        raise AttributeError(name)
    return fn
