"""Support shims for the flat C API (src/runtime/mxt_capi.h).

The C layer (src/runtime/capi.cc) is a thin marshaling bridge over an
embedded CPython; the semantics live here where they are directly
testable.  Parity targets: c_api.cc NDArray block (:153-361),
c_api_ndarray.cc MXImperativeInvoke (:80-142), c_api_executor.cc
simple-bind (:220).
"""
from __future__ import annotations

import numpy as _np

from . import ndarray as _nd_pkg
from . import nd as _nd
from .base import MXNetError
from .context import current_context
from .ndarray import NDArray


def _np_dtype(name):
    if name == "bfloat16":
        import ml_dtypes
        return _np.dtype(ml_dtypes.bfloat16)
    return _np.dtype(name)


def nd_itemsize(arr: NDArray) -> int:
    """Bytes per element of the array's dtype — the single source of
    dtype knowledge for the C layer's size checks."""
    return int(_np_dtype(str(arr.dtype)).itemsize)


def nd_create(shape, dtype="float32"):
    """Zero-filled NDArray (MXTNDArrayCreate)."""
    return _nd.zeros(tuple(int(d) for d in shape), dtype=dtype)


def nd_from_bytes(arr: NDArray, raw: bytes) -> None:
    """Raw-byte upload into an existing NDArray (SyncCopyFromCPU).
    Byte length must equal size * itemsize of the array's dtype."""
    dt = _np_dtype(str(arr.dtype))
    expect = int(arr.size) * dt.itemsize
    if len(raw) != expect:
        raise MXNetError(
            f"SyncCopyFromCPU: got {len(raw)} bytes, array wants {expect} "
            f"({arr.size} x {dt})")
    vals = _np.frombuffer(raw, dtype=dt).reshape(arr.shape)
    arr[:] = vals


def nd_to_bytes(arr: NDArray) -> bytes:
    """Raw-byte download (SyncCopyToCPU)."""
    return _np.ascontiguousarray(
        arr.asnumpy().astype(_np_dtype(str(arr.dtype)), copy=False)
    ).tobytes()


def invoke(op_name, inputs, params, outputs=None):
    """Generic op invoke (MXTImperativeInvoke).  `params` values arrive
    as strings from C; the op schema's Arg coercion parses them (same
    contract as the reference's dmlc::Parameter::Init over char**).
    `outputs` (when given) become the in-place `out=` target — the
    fused optimizer-update path."""
    fn = getattr(_nd, op_name, None)
    if fn is None or not callable(fn):
        raise MXNetError(f"unknown operator '{op_name}'")
    kw = dict(params or {})
    if outputs:
        kw["out"] = outputs[0] if len(outputs) == 1 else tuple(outputs)
    res = fn(*inputs, **kw)
    if res is None:
        return list(outputs or [])
    if isinstance(res, (list, tuple)):
        return list(res)
    return [res]


def symbol_from_json(json_str):
    from . import sym as _sym
    return _sym.load_json(json_str)


def simple_bind(sym, shapes, grad_req="write"):
    """simple_bind on the current context; missing params are created
    zero-filled by the executor machinery (MXTExecutorSimpleBind)."""
    return sym.simple_bind(current_context(), grad_req=grad_req,
                           **{k: tuple(int(d) for d in v)
                              for k, v in shapes.items()})


def save(fname, keys, arrays):
    _nd.save(fname, dict(zip(keys, arrays)))


def load(fname):
    """Returns (keys, arrays) with deterministic order; list-form files
    get stringified indices as keys (reference MXNDArrayLoad returns an
    optional name table the same way)."""
    d = _nd.load(fname)
    if isinstance(d, dict):
        keys = sorted(d)
        return keys, [d[k] for k in keys]
    return [str(i) for i in range(len(d))], list(d)


def _coerce_str(v: str):
    """Literal-coerce a string kwarg for iterator creation ("32" -> 32,
    "(3, 8, 8)" -> tuple, "true" -> True, else the string itself)."""
    low = v.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def kv_create(kind):
    from . import kvstore
    return kvstore.create(kind)


def kv_init(kv, key, value):
    kv.init(key, value)


def kv_push(kv, key, value, priority=0):
    kv.push(key, value, priority=priority)


def kv_pull(kv, key, out, priority=0):
    kv.pull(key, out=out, priority=priority)


def iter_create(name, params):
    """Create a mx.io iterator by class name with string kwargs
    (MXTDataIterCreate; parity: MXDataIterCreateIter over the iterator
    registry with char** params)."""
    from . import io as _io
    cls = getattr(_io, name, None)
    if cls is None or not callable(cls):
        raise MXNetError(f"unknown data iterator '{name}'")
    return cls(**{k: _coerce_str(v) for k, v in params.items()})


def iter_next(it):
    """Advance; returns the DataBatch or None at epoch end (the C layer
    turns this into the has-next flag + cached current batch)."""
    try:
        return next(it)
    except StopIteration:
        return None


# ---- autograd + CachedOp (MXTAutograd* / MXTCachedOp*; parity:
# c_api_ndarray.cc MXAutogradSetIsRecording/MarkVariables/
# BackwardEx + MXCreateCachedOp/MXInvokeCachedOp) ----

def autograd_set_recording(flag):
    from . import autograd
    return int(autograd.set_recording(bool(flag)))


def autograd_set_training(flag):
    from . import autograd
    return int(autograd.set_training(bool(flag)))


def autograd_is_recording():
    from . import autograd
    return int(autograd.is_recording())


def autograd_is_training():
    from . import autograd
    return int(autograd.is_training())


def autograd_mark_variables(variables, gradients):
    from . import autograd
    autograd.mark_variables(list(variables), list(gradients))


def autograd_backward(heads, head_grads, retain_graph, train_mode):
    from . import autograd
    autograd.backward(list(heads),
                      None if head_grads is None else list(head_grads),
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


def nd_grad(arr):
    if arr.grad is None:
        raise MXNetError("array has no gradient buffer; call "
                         "MXTAutogradMarkVariables on it first")
    return arr.grad


def cached_op_create(sym):
    from .gluon.block import CachedOp
    return CachedOp(sym)


def cached_op_num_outputs(cop):
    """Output count for the C layer's capacity pre-check — MUST be
    consulted before invoke so a too-small output table fails BEFORE
    any side effect (in-place aux update, tape append)."""
    return len(cop.symbol.list_outputs())


def cached_op_invoke(cop, arg_names, arg_arrays, aux_names, aux_arrays):
    """Run the compiled closure.  aux arrays (BN running stats) are
    updated IN PLACE by CachedOp.__call__ — the C caller's existing
    handles see the new values.  Under recording the call lands on the
    autograd tape, so MXTAutogradBackward flows into marked args."""
    args = dict(zip(arg_names, arg_arrays))
    auxs = dict(zip(aux_names, aux_arrays))
    return cop(args, auxs, current_context())


# ---- profiler control + introspection + NDArray views (parity:
# c_api.h MXSetProfilerConfig:220, MXSetProfilerState:228,
# MXDumpProfile:231, MXNDArraySlice:455, MXNDArrayAt:467,
# MXNDArrayReshape:485, MXListAllOpNames:850) ----

def profiler_config(mode, filename):
    from . import profiler
    profiler.profiler_set_config(mode="all" if mode else "symbolic",
                                 filename=filename)


def profiler_state(state):
    from . import profiler
    profiler.profiler_set_state("run" if state else "stop")


def profiler_dump():
    from . import profiler
    profiler.dump_profile()


def list_all_op_names():
    from .ops.registry import list_ops
    return list_ops()


def nd_reshape(arr, dims):
    """-1 infers one dimension, like the reference's MXNDArrayReshape."""
    return arr.reshape(tuple(int(d) for d in dims))


def nd_slice(arr, begin, end):
    """Validated like the reference's MXNDArraySlice (CHECK begin <=
    end <= shape[0]) — python slicing would silently clamp an
    out-of-range request into a wrong-sized view the C caller only
    notices much later."""
    begin, end = int(begin), int(end)
    n = int(arr.shape[0]) if arr.shape else 0
    if not 0 <= begin <= end <= n:
        raise MXNetError(
            f"slice [{begin}:{end}) out of range for axis-0 size {n}")
    return arr[begin:end]


def nd_at(arr, idx):
    idx = int(idx)
    n = int(arr.shape[0]) if arr.shape else 0
    if not 0 <= idx < n:
        raise MXNetError(f"index {idx} out of range for axis-0 size {n}")
    return arr[idx]
