"""Evaluation metrics (parity: python/mxnet/metric.py:44-854).

Full registry: Accuracy, TopKAccuracy, F1, Perplexity, MAE, MSE, RMSE,
CrossEntropy, NegativeLogLikelihood, PearsonCorrelation, Loss, Torch, Caffe,
CustomMetric, CompositeEvalMetric, np()/create().
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as _np

from .base import MXNetError, Registry
from .ndarray import NDArray

_REG = Registry("metric")


def check_label_shapes(labels, preds, shape=False):
    if shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape[0], preds.shape[0]
    if label_shape != pred_shape:
        raise MXNetError(f"Shape of labels {label_shape} does not match "
                         f"shape of predictions {pred_shape}")


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return f"EvalMetric: {dict(zip(*self.get()))}"

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


def register(cls):
    _REG.register(cls)
    return cls


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name) if not isinstance(name, list) else names.extend(name)
            values.append(value) if not isinstance(value, list) else values.extend(value)
        return (names, values)


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label)
            if pred.ndim > label.ndim:
                pred = _np.argmax(pred, axis=self.axis)
            pred = pred.astype("int32").ravel()
            label = label.astype("int32").ravel()
            check_label_shapes(label, pred)
            self.sum_metric += (pred == label).sum()
            self.num_inst += len(pred)


acc = Accuracy
_REG._map["acc"] = Accuracy


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert top_k > 1, "use Accuracy for top_k=1"
        self.name += f"_{top_k}"

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).astype("int32")
            assert pred.ndim == 2
            argsorted = _np.argsort(pred, axis=1)
            num_samples = pred.shape[0]
            num_classes = pred.shape[1]
            top_k = min(num_classes, self.top_k)
            for j in range(top_k):
                self.sum_metric += (
                    argsorted[:, num_classes - 1 - j].ravel() == label.ravel()).sum()
            self.num_inst += num_samples


_REG._map["top_k_acc"] = TopKAccuracy
_REG._map["top_k_accuracy"] = TopKAccuracy


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            pred, label = _as_np(pred), _as_np(label).ravel()
            pred_label = _np.argmax(pred, axis=1)
            if len(_np.unique(label)) > 2:
                raise MXNetError("F1 currently only supports binary classification.")
            tp = ((pred_label == 1) & (label == 1)).sum()
            fp = ((pred_label == 1) & (label == 0)).sum()
            fn = ((pred_label == 0) & (label == 1)).sum()
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        assert len(labels) == len(preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.reshape(-1).astype("int32")
            pred = pred.reshape(label.shape[0], -1)
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.sum(_np.log(_np.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += _np.sqrt(((label - pred) ** 2.0).mean())
            self.num_inst += 1


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            label = label.ravel()
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), _np.int64(label)]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


_REG._map["nll_loss"] = NegativeLogLikelihood


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds, shape=True)
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += _np.corrcoef(pred.ravel(), label.ravel())[0, 1]
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of the raw outputs (used with MakeLoss / gluon losses)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            self.sum_metric += _as_np(pred).sum()
            self.num_inst += _as_np(pred).size if hasattr(pred, "size") else 1


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds, shape=True)
        for pred, label in zip(preds, labels):
            label, pred = _as_np(label), _as_np(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a metric (parity: metric.np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
