"""Program introspection: per-layer cost attribution, MFU/roofline
telemetry, and a persisted perf-regression sentinel (ISSUE 13).

PR 10 collapsed training to ONE donated XLA dispatch — which made the
flight recorder blind *inside* the step: ``whole_step`` is a single
opaque span, and nothing could say which layer or pipeline stage the
time or FLOPs go to.  This module is the program-level half of the
observability story (TVM's measured cost models, arxiv 1802.04799;
TF's per-op attribution + utilization telemetry, arxiv 1605.08695):

  * **program registry** — ``note_program(name, compiled=...)`` /
    ``note_jit(name, fn, *args)`` capture each compiled program's
    ``cost_analysis()`` (analytical flops, bytes accessed), its
    ``CompiledMemoryStats`` (via ``memory.compiled_stats_dict`` — ONE
    uniform shape across jax versions), and — opt-in — its optimized
    HLO text.  Wired at every compile chokepoint: Executor
    (fwd/fwd_bwd + ``memory_analysis``), ``CachedOp`` (gluon fwd/bwd),
    ``FusedUpdater.update_all``, ``WholeStepCompiler``, and the serving
    bucket precompile.  Surfaces: ``snapshot()["programs"]``,
    ``introspect.report()``.
  * **per-layer attribution** — ``symbol.graph.GraphPlan.run`` wraps
    every step in ``jax.named_scope(<node name>)`` (and the fused
    optimizer/allreduce math in literal scopes), so HLO instruction
    metadata carries layer names through forward AND backward
    (``jvp(dense0_fwd)`` / ``transpose(jvp(dense0_fwd))``).
    ``per_layer()`` parses the captured HLO with a small per-opcode
    flops model (dot/conv exact from shapes, elementwise ≈ 1/elem) and
    groups by innermost known scope — the per-layer flops table for
    the one-dispatch whole-step program.  The same scopes show up in
    profiler/Perfetto device traces for measured per-layer *time*.
  * **MFU / roofline** — analytical flops-per-step ÷ the flight
    recorder's warmed step-time EWMA → ``mxnet_mfu``,
    ``mxnet_step_flops_per_s``, ``mxnet_step_bytes_per_s``, and
    ``mxnet_step_arithmetic_intensity`` gauges (computed at export
    only), plus an ``mxnet_flops_per_s`` counter track in the Perfetto
    export.  Peak flops come from a per-platform table;
    ``MXNET_PEAK_FLOPS`` overrides (set it for meaningful MFU — the
    CPU default is a nominal placeholder).
  * **perf-regression sentinel** — per (model signature, platform)
    baselines of {step-time p50, dispatches/step, flops, HBM peak}
    persist under ``MXNET_PERF_BASELINE_DIR`` (default: a
    ``perf-baselines/`` sibling inside ``MXNET_COMPILE_CACHE_DIR``,
    like the compile cache itself).  At runtime the warmed EWMA is
    compared against the stored p50; drift past ``REGRESSION_FACTOR``
    fires ONE loud warning + ``mxnet_perf_regressions_total``
    increment (rate-limited) and flips the ``perf_regression``
    ``readyz()`` check until the regression clears or
    ``refresh_baseline()`` records the intentional change.  These
    persisted measurements are the substrate the ROADMAP's
    profile-guided autotuning tier will search over.

Overhead contract (the ``MXNET_METRICS_ENABLED`` discipline):
``MXNET_INTROSPECT=0`` reduces every hook — named scopes, program
notes, sentinel ticks — to ONE module-global boolean test.  Enabled,
the steady-state per-step cost is one counter increment (captures are
once-per-program retraces at build time, never per step); HLO text is
captured only under ``MXNET_INTROSPECT_HLO=1`` (size-capped; dumps go
through ``base.atomic_write`` + ``base.unique_path`` like flight
dumps) because it forces an extra ``lower().compile()`` on jit-called
programs.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import re
import time
from typing import Dict, List, Optional, Tuple

import jax

from ..base import MXNetError, atomic_write, getenv, unique_path
from ..analysis import sanitizer as _san

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "enabled", "enable", "disable", "layer_scope",
           "known_scopes", "note_program", "note_jit", "programs",
           "per_layer", "attributed_pct", "step_flops", "mfu",
           "peak_flops", "phase_flops_map", "dump_hlo", "report",
           "snapshot_summary", "sentinel_tick", "refresh_baseline",
           "baseline_dir", "baseline_path", "sentinel_armed",
           "regression_active", "sentinel_state", "reset", "configure"]

# -- the fast-path switch ----------------------------------------------------
# Hooks across symbol/executor/gluon/optimizer/serving read this module
# global directly: `if introspect.ENABLED: ...`.
ENABLED: bool = getenv("MXNET_INTROSPECT", True)
#: opt-in optimized-HLO text capture (per_layer()'s input).  Default
#: OFF for steady state: on jit-called programs it forces one extra
#: lower().compile() per program (persistent-compile-cache assisted).
HLO: bool = getenv("MXNET_INTROSPECT_HLO", False)
#: size cap on captured HLO text per program (truncated past it — the
#: flops parser still sees the leading instructions; configure() tunes)
HLO_CAP_BYTES: int = 8 << 20
#: sentinel check cadence, in sentinel_tick() calls per phase
SENTINEL_EVERY: int = 25
#: regression trigger: warmed EWMA > factor x persisted baseline p50
REGRESSION_FACTOR: float = 1.5
#: minimum seconds between PERF_REGRESSION firings per phase (tests 0)
REGRESSION_MIN_S: float = 300.0

#: the per-layer row every instruction lands in when no known scope is
#: found in its metadata (glue ops outside any named block)
UNATTRIBUTED = "_unattributed"

#: training-step phase -> program name the MFU/sentinel math pairs it
#: with (the fused path's step splits across three programs)
PHASE_PROGRAM = {"whole_step": "whole_step", "trainer_step": "fused_update",
                 "superstep": "superstep"}
#: programs whose flops sum to one FUSED-path training step (CachedOp
#: bwd recomputes the forward inside its fused vjp program)
FUSED_STEP_PROGRAMS = ("gluon:fwd", "gluon:bwd", "fused_update")
#: phases whose flight span covers the WHOLE training step — only these
#: may serve as the denominator for step-flops rates.  The fused path's
#: "trainer_step" span times Trainer.step alone (allreduce+update; the
#: user's fwd/bwd run outside it), so dividing full-step flops by it
#: would overstate MFU severalfold — fused-path MFU needs an explicit
#: step_time_s (the bench mfu rider measures its own).
#: "superstep" qualifies too: its span covers K whole steps and its
#: noted program's cost_analysis flops are K x one step, so the
#: flops/time quotient stays a true device rate.
FULL_STEP_PHASES = frozenset({"whole_step", "superstep"})

_lock = _san.make_lock("introspect.programs")
_programs: Dict[str, dict] = {}
#: every name ever passed through layer_scope() — the known-scope set
#: per_layer() matches HLO metadata components against.  Bounded by
#: the graphs traced in-process (one entry per distinct node name),
#: the same boundedness contract as flight phase names.
_scopes: set = set()


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


# -- named scopes ------------------------------------------------------------
@contextlib.contextmanager
def layer_scope(name: str):
    """Wrap a traced region in ``jax.named_scope(name)`` and register
    ``name`` as a known layer scope.  ``GraphPlan.run`` calls this per
    graph step with the node name (so HLO metadata carries layer names
    through fwd AND the vjp), the fused optimizer math with literal
    ``"optimizer"``/``"allreduce"`` scopes.  Names must come from a
    bounded set (graph node names / literals) — the metrics-hygiene
    graft-lint rule rejects call-site string building.  One boolean
    test when introspection is off."""
    if not ENABLED:
        yield
        return
    _scopes.add(name)
    try:
        ctx = jax.named_scope(name)
    except Exception:  # noqa: BLE001 — a bad name must never kill a trace
        yield
        return
    with ctx:
        yield


def known_scopes() -> frozenset:
    # list() snapshots the set in one GIL-atomic C call: a trace on
    # another thread may be registering scopes concurrently
    return frozenset(list(_scopes))


# -- program capture ---------------------------------------------------------
def _cost_of(compiled, lowered) -> dict:
    """Normalize jax's cost_analysis() across versions/stages: compiled
    returns a list-of-dicts on some versions, lowered a plain dict.
    Uniform output: {"flops": float, "bytes": float} (keys present only
    when the backend reports them)."""
    src = compiled if compiled is not None else lowered
    if src is None:
        return {}
    try:
        ca = src.cost_analysis()
    except Exception:  # noqa: BLE001 — stats are best-effort
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out = {}
    if ca.get("flops") is not None:
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed") is not None:
        out["bytes"] = float(ca["bytes accessed"])
    return out


def _memory_of(compiled) -> dict:
    if compiled is None:
        return {}
    from . import memory as _memory
    try:
        return _memory.compiled_stats_dict(compiled.memory_analysis())
    except Exception:  # noqa: BLE001
        return {}


def _hlo_of(compiled, lowered) -> Tuple[Optional[str], bool]:
    """Optimized HLO text, size-capped.  Lazy by flag: nothing is ever
    rendered unless MXNET_INTROSPECT_HLO=1 — and only then does a
    jit-called program pay the extra lowered.compile() (which the
    persistent compile cache absorbs when MXNET_COMPILE_CACHE_DIR is
    set)."""
    if not HLO:
        return None, False
    src = compiled
    if src is None and lowered is not None:
        try:
            src = lowered.compile()
        except Exception:  # noqa: BLE001
            return None, False
    if src is None:
        return None, False
    try:
        txt = src.as_text()
    except Exception:  # noqa: BLE001
        return None, False
    if not isinstance(txt, str) or not txt:
        return None, False
    if len(txt) > HLO_CAP_BYTES:
        return txt[:HLO_CAP_BYTES], True
    return txt, False


def note_program(name: str, compiled=None, lowered=None, label=None,
                 signature=None, memory_stats=None,
                 contracts=None) -> dict:
    """File one compiled program's stats under ``name`` — THE shared
    surface every compile chokepoint routes through (Executor bind /
    memory_analysis, CachedOp, FusedUpdater, WholeStepCompiler, serving
    bucket precompile).

    ``name`` must be a bounded literal; a varying-but-bounded qualifier
    (the serving bucket label) goes in ``label`` and is joined as
    ``name:label`` here, mirroring the flight recorder's bucket_label
    discipline.  ``memory_stats`` short-circuits the CompiledMemoryStats
    read for callers that already hold the uniform dict.  Captured
    memory stats are also filed into the HBM ledger's compiled table
    (``memory.report()["compiled"]``) so that surface keeps one source.

    ``contracts`` (ISSUE 15) declares what the LOWERED artifact must
    look like — ``{"donate_argnums": ..., "donated_leaves": n,
    "amp": policy, "host_callbacks": 0, "collectives": 0}`` — which
    ``analysis.audit_programs()`` verifies against the captured HLO
    (donation really became input-output aliasing, AMP left no f32
    dots, no host callbacks, collective count matches the bucketer's
    plan).  Returns the record (``{}`` when introspection is off)."""
    if not ENABLED:
        return {}
    from . import goodput as _goodput
    if _goodput.ENABLED:
        # training compiles happen inside jax where their duration is
        # invisible here — count the event (serving precompile, which
        # owns its compile call, attributes measured seconds)
        _goodput.note_event("recompile")
    full = name if label is None else f"{name}:{label}"
    cost = _cost_of(compiled, lowered)
    mem = memory_stats if memory_stats is not None else _memory_of(compiled)
    if mem:
        from . import memory as _memory
        _memory.note_compiled(full, mem)
    hlo, truncated = _hlo_of(compiled, lowered)
    with _lock:
        prev = _programs.get(full)
        rec = {
            "name": full,
            "flops": cost.get("flops"),
            "bytes": cost.get("bytes"),
            "memory": dict(mem) if mem else {},
            "signature": signature if signature is not None
            else (prev or {}).get("signature"),
            "hlo": hlo if hlo is not None else (prev or {}).get("hlo"),
            "hlo_truncated": truncated if hlo is not None
            else bool((prev or {}).get("hlo_truncated")),
            "contracts": dict(contracts) if contracts is not None
            else (prev or {}).get("contracts"),
            "captures": ((prev or {}).get("captures") or 0) + 1,
        }
        _programs[full] = rec
        return dict(rec)


def note_jit(name: str, fn, *args, label=None, signature=None,
             contracts=None, **kwargs) -> dict:
    """Capture a jit-called program via ``fn.lower(*args)`` — a retrace
    (NO XLA compile unless MXNET_INTROSPECT_HLO=1 forces one for the
    text).  Call sites guard to once per program/cache key; a capture
    failure is logged and swallowed — introspection must never break
    the step it observes."""
    if not ENABLED:
        return {}
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception as e:  # noqa: BLE001
        log.debug("introspect: lowering %s for capture failed: %s", name, e)
        return {}
    return note_program(name, lowered=lowered, label=label,
                        signature=signature, contracts=contracts)


def programs() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _programs.items()}


def dump_hlo(name: str, directory: Optional[str] = None) -> str:
    """Write one program's captured HLO text to disk (atomic,
    collision-free timestamped filename — the flight-dump policy).
    Default directory: ``MXNET_FLIGHT_DIR``."""
    rec = programs().get(name)
    if rec is None or not rec.get("hlo"):
        raise MXNetError(
            f"no HLO captured for program {name!r} — set "
            f"MXNET_INTROSPECT_HLO=1 before the program compiles "
            f"(captured: {sorted(programs())})")
    d = directory or os.environ.get("MXNET_FLIGHT_DIR", ".") or "."
    os.makedirs(d, exist_ok=True)
    safe = re.sub(r"[^\w.-]", "-", name)
    path = unique_path(d, f"hlo-{safe}", ".txt")
    atomic_write(path, rec["hlo"])
    return path


# -- per-layer flops attribution ---------------------------------------------
# Opcodes that move/route data but compute nothing (match XLA's own
# HloCostAnalysis, which costs these 0 flops)
_ZERO_FLOP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "bitcast-convert", "copy", "copy-start", "copy-done", "reshape",
    "broadcast", "transpose", "slice", "concatenate", "iota", "pad",
    "dynamic-slice", "dynamic-update-slice", "fusion", "call", "while",
    "conditional", "custom-call", "get-dimension-size", "after-all",
    "rng-bit-generator", "rng", "partition-id", "replica-id", "gather",
    "convert", "reverse", "domain", "infeed", "outfeed", "send", "recv",
    "send-done", "recv-done", "all-gather", "optimization-barrier",
})

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.+?)\s+"
                       r"([\w\-]+)\(")
_DIMS_RE = re.compile(r"\[([0-9,]*)\]")
_META_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]+)"')
_WRAP_RE = re.compile(r"^[\w\-]+\((.*)\)$")


def _prod_dims(spec: str) -> int:
    n = 1
    for d in spec.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n


def _all_dims_prod(type_str: str) -> int:
    """Sum of element counts over every array shape in a (possibly
    tuple) HLO result type."""
    total = 0
    for m in _DIMS_RE.finditer(type_str):
        total += _prod_dims(m.group(1))
    return total if total else 1


def _operand_dims(line: str, opcode: str) -> List[List[int]]:
    seg = line.split(opcode + "(", 1)
    if len(seg) < 2:
        return []
    out = []
    for m in _DIMS_RE.finditer(seg[1].split(" metadata=")[0]):
        out.append([int(d) for d in m.group(1).split(",") if d.strip()])
    return out


def _instr_flops(line: str, type_str: str, opcode: str) -> float:
    """Per-instruction flops model: dot/conv exact from shapes (2 flops
    per MAC, XLA's convention), reduce ≈ input elements, everything
    else ≈ 1 flop per output element.  Conservative where it cannot
    parse — the attribution acceptance runs against this model's own
    total, and dots/convs dominate real training programs."""
    out_elems = _all_dims_prod(type_str)
    if opcode == "dot":
        ops = _operand_dims(line, opcode)
        m = re.search(r"lhs_contracting_dims=\{([0-9,]+)\}", line)
        if ops and m:
            lhs = ops[0]
            contracted = 1
            for i in m.group(1).split(","):
                i = int(i)
                if i < len(lhs):
                    contracted *= lhs[i]
            return 2.0 * out_elems * contracted
        return 2.0 * out_elems
    if opcode == "convolution":
        window = 1
        m = re.search(r"window=\{[^}]*size=([0-9x]+)", line)
        if m:
            for d in m.group(1).split("x"):
                window *= int(d)
        kin = 1
        m = re.search(r"dim_labels=(\S+)", line)
        ops = _operand_dims(line, opcode)
        if m and len(ops) >= 2 and "_" in m.group(1):
            klabels = m.group(1).split("_", 1)[1].split("->", 1)[0]
            pos = klabels.find("i")
            if 0 <= pos < len(ops[1]):
                kin = ops[1][pos]
        return 2.0 * out_elems * window * kin
    if opcode in ("reduce", "reduce-window", "scatter", "select-and-scatter",
                  "sort", "all-reduce"):
        ops = _operand_dims(line, opcode)
        if ops and ops[0]:
            n = 1
            for d in ops[0]:
                n *= d
            return float(n)
        return float(out_elems)
    return float(out_elems)


def _scope_of(op_name: str, known: frozenset) -> Optional[str]:
    """Innermost known layer scope in an HLO op_name path.  Components
    arrive decorated by the tracing machinery — ``jvp(dense0_fwd)``,
    ``transpose(jvp(dense0_fwd))``, ``rematted_computation(...)`` — so
    each is unwrapped to its innermost token before the known-set
    test."""
    best = None
    for comp in op_name.split("/"):
        t = comp
        while True:
            m = _WRAP_RE.match(t)
            if m is None:
                break
            t = m.group(1)
        if t in known:
            best = t
    return best


def _layer_of(scope: str) -> str:
    """Scope name -> layer row: graph node names carry an op-derived
    ``_fwd`` suffix (``hybridsequential0_dense0_fwd``) that per-layer
    grouping strips; literal scopes (``optimizer``) pass through."""
    return scope[:-4] if scope.endswith("_fwd") else scope


def parse_hlo_flops(text: str,
                    known: Optional[frozenset] = None) -> Dict[str, float]:
    """Parse optimized HLO text into ``{layer: flops}`` (instructions
    inside fusion computations carry their own metadata, so fused ops
    still attribute; the ``fusion``/``call`` container instructions
    themselves cost 0).  Instructions without a known scope land under
    ``_unattributed``."""
    known = known if known is not None else known_scopes()
    out: Dict[str, float] = {}
    for line in text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        type_str, opcode = m.group(1), m.group(2)
        if opcode in _ZERO_FLOP_OPS:
            continue
        flops = _instr_flops(line, type_str, opcode)
        if flops <= 0:
            continue
        meta = _META_RE.search(line)
        scope = _scope_of(meta.group(1), known) if meta else None
        layer = _layer_of(scope) if scope else UNATTRIBUTED
        out[layer] = out.get(layer, 0.0) + flops
    return out


def per_layer(program: str = "whole_step", top: Optional[int] = None,
              step_time_s: Optional[float] = None,
              phase: Optional[str] = None) -> List[dict]:
    """The per-layer cost table for a captured program: ``[{layer,
    flops, pct, est_ms}]`` sorted by flops (the ``_unattributed``
    remainder is a row, never hidden).  ``est_ms`` distributes the
    phase's warmed step-time EWMA (or ``step_time_s``) proportionally
    to flops — the cheap always-available time estimate; for MEASURED
    per-layer time, take a profiler/Perfetto device trace: its op
    metadata carries the same named scopes.  Requires HLO capture
    (``MXNET_INTROSPECT_HLO=1`` before the program compiles)."""
    rec = programs().get(program)
    if rec is None:
        raise MXNetError(
            f"program {program!r} has not been captured "
            f"(captured: {sorted(programs())})")
    if not rec.get("hlo"):
        raise MXNetError(
            f"no HLO text captured for {program!r}: set "
            f"MXNET_INTROSPECT_HLO=1 (or configure(hlo=True)) before "
            f"the program compiles — capture is opt-in because it "
            f"forces an extra lower().compile() per program")
    by_layer = parse_hlo_flops(rec["hlo"])
    total = sum(by_layer.values()) or 1.0
    if step_time_s is None:
        from . import flight as _flight
        for ph in ([phase] if phase else
                   [p for p, pr in PHASE_PROGRAM.items() if pr == program] +
                   [program]):
            step_time_s = _flight.watch_ewma(ph)
            if step_time_s is not None:
                break
    rows = [{"layer": k, "flops": v,
             "pct": round(100.0 * v / total, 2),
             "est_ms": round(step_time_s * 1e3 * v / total, 4)
             if step_time_s else None}
            for k, v in sorted(by_layer.items(), key=lambda kv: -kv[1])]
    return rows[:top] if top else rows


def attributed_pct(program: str = "whole_step") -> float:
    """Fraction (pct) of the parsed program flops attributed to NAMED
    blocks — the ISSUE 13 >=90% acceptance number."""
    rows = per_layer(program)
    return round(sum(r["pct"] for r in rows
                     if r["layer"] != UNATTRIBUTED), 2)


# -- MFU / roofline ----------------------------------------------------------
# Nominal dense peak flops by device kind (f32/bf16 MXU peaks for TPU
# generations; the CPU entry is a PLACEHOLDER so the math runs — set
# MXNET_PEAK_FLOPS for a meaningful MFU on your part)
_PEAK_TABLE = (
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_CPU_NOMINAL_PEAK = 1e11


def peak_flops() -> Tuple[float, str]:
    """(peak flops/s, source): MXNET_PEAK_FLOPS override > device-kind
    table > nominal CPU placeholder."""
    override = float(getenv("MXNET_PEAK_FLOPS", 0.0))
    if override > 0:
        return override, "MXNET_PEAK_FLOPS"
    try:
        dev = jax.local_devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").lower()
        if dev.platform == "tpu":
            for tag, peak in _PEAK_TABLE:
                if tag in kind:
                    return peak, f"table:{tag}"
            return 123e12, "table:tpu-default"
    except Exception:  # noqa: BLE001
        pass
    return _CPU_NOMINAL_PEAK, "nominal-cpu"


def step_flops() -> Tuple[Optional[float], Optional[float], Optional[str]]:
    """(flops, bytes, phase) for one training step, from the noted
    programs: the whole-step program when captured, else the sum of the
    fused path's three programs (CachedOp's bwd recomputes the forward
    inside its fused vjp, so the sum is what actually executes)."""
    progs = programs()
    rec = progs.get("whole_step")
    if rec is not None and rec.get("flops"):
        return rec["flops"], rec.get("bytes"), "whole_step"
    parts = [progs[n] for n in FUSED_STEP_PROGRAMS if n in progs]
    if parts and any(p.get("flops") for p in parts):
        return (sum(p.get("flops") or 0.0 for p in parts),
                sum(p.get("bytes") or 0.0 for p in parts) or None,
                "trainer_step")
    return None, None, None


def mfu(step_time_s: Optional[float] = None, flops: Optional[float] = None,
        bytes_per_step: Optional[float] = None,
        peak: Optional[float] = None) -> dict:
    """MFU + roofline telemetry: analytical flops/step ÷ measured step
    time ÷ platform peak.  Every input is overridable (the bench rider
    passes its own measured step time); defaults come from the noted
    programs + the flight recorder's warmed EWMA.  Returns ``{}`` when
    either the flops or the step time is not yet measurable."""
    phase = None
    if flops is None:
        flops, b, phase = step_flops()
        if bytes_per_step is None:
            bytes_per_step = b
    if flops is None or flops <= 0:
        return {}
    if step_time_s is None and phase in FULL_STEP_PHASES:
        from . import flight as _flight
        step_time_s = _flight.watch_ewma(phase)
    if not step_time_s or step_time_s <= 0:
        return {}
    pk, src = (peak, "caller") if peak else peak_flops()
    fps = flops / step_time_s
    out = {
        "flops_per_step": flops,
        "step_time_ms": round(step_time_s * 1e3, 4),
        "flops_per_s": fps,
        "peak_flops": pk,
        "peak_source": src,
        "mfu": round(fps / pk, 6),
        "mfu_pct": round(100.0 * fps / pk, 4),
    }
    if bytes_per_step:
        out["bytes_per_step"] = bytes_per_step
        out["bytes_per_s"] = bytes_per_step / step_time_s
        out["arithmetic_intensity"] = round(flops / bytes_per_step, 4)
    try:
        from ..parallel.mesh import current_mesh, mesh_signature
        m = current_mesh()
        if m is not None:
            # the sharded-run attribution: total program flops split by
            # each mesh axis's size — the per-shard share along that
            # axis (metrics.py exports these as per-axis gauge children)
            out["mesh"] = mesh_signature(m)
            out["mesh_axes"] = {a: int(m.shape[a]) for a in m.axis_names}
            out["per_axis_flops_per_s"] = {
                a: fps / int(m.shape[a]) for a in m.axis_names}
    except Exception:  # noqa: BLE001 — telemetry must never fail a pull
        pass
    return out


def phase_flops_map() -> Dict[str, float]:
    """{flight phase name: analytical flops/step} for the phases whose
    spans cover a whole training step — the feed for the Perfetto
    ``mxnet_flops_per_s`` counter track (timeline.chrome_events).
    Restricted to FULL_STEP_PHASES: emitting the fused path's
    fwd+bwd+update flops over the "trainer_step" span (which times only
    allreduce+update) would render impossible flops/s."""
    flops, _b, phase = step_flops()
    return {phase: flops} if phase in FULL_STEP_PHASES and flops else {}


# -- perf-regression sentinel ------------------------------------------------
_BASELINE_SCHEMA = 1
_BASELINE_KEYS = ("step_time_p50_ms", "dispatches_per_step",
                  "flops_per_step", "hbm_peak_bytes")
_sent_counts: Dict[str, int] = {}
_sentinel: Dict[str, dict] = {}


def baseline_dir() -> Optional[str]:
    """Where baselines persist: ``MXNET_PERF_BASELINE_DIR``, else a
    ``perf-baselines/`` directory next to the persistent compile cache
    (``MXNET_COMPILE_CACHE_DIR``).  None disarms the sentinel."""
    d = os.environ.get("MXNET_PERF_BASELINE_DIR")
    if d:
        return d
    c = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    return os.path.join(c, "perf-baselines") if c else None


def _platform() -> str:
    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001
        return "unknown"


def _signature_of(phase: str) -> str:
    rec = programs().get(PHASE_PROGRAM.get(phase, phase))
    sig = (rec or {}).get("signature")
    return sig or "unsigned"


def baseline_path(phase: str) -> Optional[str]:
    d = baseline_dir()
    if d is None:
        return None
    return os.path.join(
        d, f"{phase}-{_signature_of(phase)}-{_platform()}.json")


def _current_measurements(phase: str) -> Optional[dict]:
    from . import flight as _flight
    from . import metrics as _metrics
    ewma = _flight.watch_ewma(phase)
    if ewma is None:
        return None
    rec = programs().get(PHASE_PROGRAM.get(phase, phase))
    hbm = 0
    try:
        from . import memory as _memory
        if _memory.ENABLED:
            _dev, _host, peaks = _memory._live_split()
            hbm = int(sum(v for (sp, _t), v in peaks.items()
                          if sp == "device"))
    except Exception:  # noqa: BLE001
        pass
    return {
        "schema": _BASELINE_SCHEMA,
        "phase": phase,
        "platform": _platform(),
        "signature": _signature_of(phase),
        # the persisted "p50" is the warmed EWMA — the same robust
        # location estimate the runtime comparison reads, so write and
        # compare can never disagree on methodology
        "step_time_p50_ms": round(ewma * 1e3, 4),
        # the superstep phase gates on its own gauge: scanned = 1 per
        # K-step superstep, ~K after a silent demotion — which is the
        # regression this baseline exists to catch
        "dispatches_per_step": float(
            _metrics.SUPERSTEP_DISPATCHES.get() if phase == "superstep"
            else _metrics.TRAINER_STEP_DISPATCHES.get()),
        "flops_per_step": (rec or {}).get("flops"),
        "hbm_peak_bytes": hbm,
        "written_at": time.time(),
    }


def _sentinel_entry(phase: str) -> dict:
    ent = _sentinel.get(phase)
    if ent is None:
        ent = _sentinel[phase] = {
            "baseline": None, "loaded": False, "corrupt": False,
            "active": False, "kind": None, "fired_at": None,
            "pending": False, "path": None, "wrote": False,
            "sig": None,
        }
    return ent


def _load_baseline(phase: str, ent: dict) -> None:
    ent["loaded"] = True
    path = baseline_path(phase)
    ent["path"] = path
    if path is None or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or \
                data.get("schema") != _BASELINE_SCHEMA or \
                not isinstance(data.get("step_time_p50_ms"), (int, float)) \
                or data["step_time_p50_ms"] <= 0:
            raise ValueError("missing/invalid required fields")
    except Exception as e:  # noqa: BLE001 — reject loudly, never crash
        ent["corrupt"] = True
        log.warning(
            "perf-regression sentinel: baseline %s is corrupt (%s) — "
            "REJECTED; the sentinel stays disarmed for this phase until "
            "introspect.refresh_baseline(%r) rewrites it", path, e, phase)
        return
    ent["baseline"] = data


def _write_baseline(phase: str, cur: dict, ent: dict) -> None:
    path = baseline_path(phase)
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write(path, json.dumps(cur, indent=1, sort_keys=True))
        ent["baseline"] = cur
        ent["path"] = path
        ent["wrote"] = True
        log.info("perf-regression sentinel: wrote baseline %s "
                 "(p50 %.3f ms)", path, cur["step_time_p50_ms"])
    except OSError as e:
        log.warning("perf-regression sentinel: baseline write to %s "
                    "failed: %s", path, e)


def sentinel_tick(phase: str) -> None:
    """Per-step hook at the training chokepoints (Trainer.step /
    WholeStepCompiler._dispatch).  One boolean + one counter increment
    per step; the real check runs every SENTINEL_EVERY steps once the
    phase's EWMA has warmed."""
    if not ENABLED:
        return
    n = _sent_counts.get(phase, 0) + 1
    _sent_counts[phase] = n
    if n % SENTINEL_EVERY:
        return
    try:
        _sentinel_check(phase)
    except Exception as e:  # noqa: BLE001 — never break the step
        log.debug("perf sentinel check failed: %s", e)


def _sentinel_check(phase: str) -> None:
    if baseline_dir() is None:
        return
    cur = _current_measurements(phase)
    if cur is None:
        return  # EWMA not warmed yet
    ent = _sentinel_entry(phase)
    sig = _signature_of(phase)
    if ent["loaded"] and ent.get("sig") != sig:
        # the program's signature moved mid-run (a legitimate batch or
        # config change re-noted it): the cached baseline belongs to
        # the OLD workload — re-resolve against the new signature's
        # file instead of firing a false regression
        prev = _sentinel[phase] = dict(ent, loaded=False, baseline=None,
                                       corrupt=False, active=False,
                                       kind=None, pending=False)
        ent = prev
    if not ent["loaded"]:
        ent["sig"] = sig
        _load_baseline(phase, ent)
    if ent["baseline"] is None:
        if not ent["corrupt"]:
            _write_baseline(phase, cur, ent)
        return
    base = ent["baseline"]
    kind = None
    if cur["step_time_p50_ms"] > REGRESSION_FACTOR * \
            base["step_time_p50_ms"]:
        kind = "step_time"
    elif base.get("dispatches_per_step") and \
            cur["dispatches_per_step"] > base["dispatches_per_step"] + 0.5:
        kind = "dispatches"
    ent["current"] = cur
    if kind is None:
        ent["active"] = False
        ent["kind"] = None
        ent["pending"] = False
        return
    if ent["active"] and not ent.get("pending"):
        return  # still the same regression episode — fired already
    ent["active"] = True
    ent["kind"] = kind
    now = time.monotonic()
    if ent["fired_at"] is not None and \
            now - ent["fired_at"] < REGRESSION_MIN_S:
        # inside the rate window: DEFER the fire, never drop it — an
        # episode that begins here and persists must still warn and
        # count on the first check after the window elapses (readyz
        # flips immediately either way via ent["active"])
        ent["pending"] = True
        return
    ent["pending"] = False
    ent["fired_at"] = now
    log.warning(
        "PERF REGRESSION (%s) on %s: step-time p50 %.3f ms vs baseline "
        "%.3f ms (factor %.1f), dispatches/step %.1f vs %.1f — baseline "
        "%s; if this change is intentional, refresh it with "
        "mx.observability.introspect.refresh_baseline(%r)",
        kind, phase, cur["step_time_p50_ms"], base["step_time_p50_ms"],
        REGRESSION_FACTOR, cur["dispatches_per_step"],
        base.get("dispatches_per_step", 0.0), ent["path"], phase)
    from . import metrics as _metrics
    if _metrics.ENABLED:
        # kind/phase are bounded literal sets (step_time|dispatches x
        # whole_step|trainer_step)
        _metrics.PERF_REGRESSIONS.inc(kind=kind, phase=phase)
    from . import journal as _journal
    if _journal.ENABLED:
        _journal.emit("perf_regression", durable=True, kind=kind,
                      phase=phase,
                      current_p50_ms=cur["step_time_p50_ms"],
                      baseline_p50_ms=base["step_time_p50_ms"])


def refresh_baseline(phase: str = "whole_step") -> Optional[dict]:
    """Rewrite the persisted baseline from CURRENT warmed measurements
    — the intentional-change lifecycle step (a deliberate model/config
    change that moves step time must not page forever).  Clears any
    active regression for the phase.  Returns the written baseline
    (None when the EWMA has not warmed or no baseline dir is set)."""
    if not ENABLED or baseline_dir() is None:
        return None
    cur = _current_measurements(phase)
    if cur is None:
        return None
    ent = _sentinel_entry(phase)
    ent["loaded"] = True
    ent["sig"] = _signature_of(phase)
    ent["corrupt"] = False
    ent["active"] = False
    ent["kind"] = None
    ent["pending"] = False
    _write_baseline(phase, cur, ent)
    return dict(cur)


def sentinel_armed() -> bool:
    """True once any phase has a loaded baseline to compare against.
    list() snapshots against a supervised worker thread's sentinel_tick
    inserting a phase entry mid-iteration (the readyz watchdog calls
    this from the server thread)."""
    return any(e.get("baseline") is not None
               for e in list(_sentinel.values()))


def regression_active() -> bool:
    return any(e.get("active") for e in list(_sentinel.values()))


def sentinel_state() -> dict:
    """snapshot()-able sentinel block: per-phase baseline/current/
    active state + the resolved baseline directory.  Iterates a
    GIL-atomic list() snapshot — a training thread may be inserting a
    phase entry while a readyz/scrape thread renders this."""
    phases = {}
    for phase, e in sorted(list(_sentinel.items())):
        base, cur = e.get("baseline"), e.get("current")
        phases[phase] = {
            "baseline": dict(base) if base else None,
            "current": dict(cur) if cur else None,
            "active": bool(e.get("active")),
            "kind": e.get("kind"),
            "corrupt": bool(e.get("corrupt")),
            "path": e.get("path"),
        }
    return {"dir": baseline_dir(), "armed": sentinel_armed(),
            "regression_active": regression_active(), "phases": phases}


# -- surfaces ----------------------------------------------------------------
def snapshot_summary() -> dict:
    """The compact block ``observability.snapshot()["programs"]``
    carries: per-program flops/bytes/peak + MFU + sentinel state."""
    progs = {}
    for name, rec in sorted(programs().items()):
        progs[name] = {
            "flops": rec.get("flops"),
            "bytes": rec.get("bytes"),
            "peak_bytes": (rec.get("memory") or {}).get("peak_bytes"),
            "signature": rec.get("signature"),
            "hlo_captured": bool(rec.get("hlo")),
            "captures": rec.get("captures", 0),
        }
    return {"enabled": ENABLED, "hlo": HLO, "programs": progs,
            "mfu": mfu(), "sentinel": sentinel_state(),
            "known_scopes": len(_scopes)}


def report() -> dict:
    """The operator's one-stop view: full program records (HLO elided
    to a length), per-layer tables where HLO was captured, MFU, and
    sentinel state."""
    out = {"enabled": ENABLED, "hlo": HLO, "mfu": mfu(),
           "sentinel": sentinel_state(), "programs": {}, "per_layer": {}}
    for name, rec in sorted(programs().items()):
        r = dict(rec)
        hlo = r.pop("hlo", None)
        r["hlo_bytes"] = len(hlo) if hlo else 0
        out["programs"][name] = r
        if hlo:
            try:
                out["per_layer"][name] = per_layer(name)
            except MXNetError:
                pass
    return out


# -- lifecycle ---------------------------------------------------------------
def reset() -> None:
    """Drop every program record, known scope, and sentinel state
    (tests).  On-disk baselines are untouched — delete the file or
    refresh_baseline() to change them."""
    with _lock:
        _programs.clear()
    _scopes.clear()
    _sent_counts.clear()
    _sentinel.clear()


def configure(hlo: Optional[bool] = None,
              hlo_cap_bytes: Optional[int] = None,
              sentinel_every: Optional[int] = None,
              regression_factor: Optional[float] = None,
              regression_min_s: Optional[float] = None) -> None:
    """Tune knobs at runtime.  Every parameter follows the same rule:
    None leaves the current value UNCHANGED (a call tuning only the
    sentinel cadence must not silently reset HLO capture from the
    env — env values are read once at import)."""
    global HLO, HLO_CAP_BYTES, SENTINEL_EVERY, REGRESSION_FACTOR, \
        REGRESSION_MIN_S
    if hlo is not None:
        HLO = bool(hlo)
    if hlo_cap_bytes is not None:
        HLO_CAP_BYTES = max(1, int(hlo_cap_bytes))
    if sentinel_every is not None:
        SENTINEL_EVERY = max(1, int(sentinel_every))
    if regression_factor is not None:
        REGRESSION_FACTOR = float(regression_factor)
    if regression_min_s is not None:
        REGRESSION_MIN_S = float(regression_min_s)
