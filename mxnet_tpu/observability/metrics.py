"""Metrics registry: counters, gauges, histograms with labels +
Prometheus-text / JSON exporters.

Design rules (set by the round-2 regression this subsystem exists to
catch — instrumentation must never become the overhead it measures):

  - module-level fast-path flag: every runtime hook reads `ENABLED`
    (plain module global) before touching a metric, so
    MXNET_METRICS_ENABLED=0 costs one boolean test per hook;
  - stable identity: metrics are created ONCE at import and looked up by
    attribute, never by name on the hot path — `inc()` on the unlabeled
    fast path is a single float add, no dict allocation;
  - on-demand expensive data: HBM usage (`device.memory_stats()`) is
    sampled inside `collect()`/`snapshot()`, never per-step.

Prometheus text format follows the exposition format spec close enough
for a scrape endpoint (`# TYPE` lines, `{label="v"}` selectors,
histogram `_bucket`/`_sum`/`_count` series with cumulative `le`).
"""
from __future__ import annotations

import json as _json
import threading
from typing import Dict, List, Optional, Tuple

from ..base import getenv
from ..analysis.sanitizer import make_lock as _make_lock

# -- the fast-path switch ----------------------------------------------------
# Hooks across engine/executor/kvstore/io read this module global directly:
#   if metrics.ENABLED: metrics.XLA_LAUNCHES.inc(...)
# bool default activates getenv's tolerant parsing ("0"/"false"/"" off)
ENABLED: bool = getenv("MXNET_METRICS_ENABLED", True)


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


# -- metric primitives -------------------------------------------------------
# One shared mutation lock: hooks fire from the training thread AND from
# data-pipeline producer threads (PrefetchingIter, DataLoader pools); an
# unguarded read-modify-write would drop increments and corrupt the
# exact-count invariant dispatch_counts() advertises.  Contention is a
# few acquisitions per training step — noise next to an XLA dispatch.
# (sanitizer factory: a plain threading.Lock unless MXNET_SANITIZE=1,
# in which case it joins the lock-order graph as "metrics.mut")
_MUT_LOCK = _make_lock("metrics.mut")


def _label_key(labels: dict) -> Tuple:
    return tuple(sorted(labels.items()))


class Metric:
    """Base: name + help + label-set → value(s)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", registry=None):
        self.name = name
        self.help = help
        (registry if registry is not None else REGISTRY)._register(self)

    def reset(self) -> None:
        raise NotImplementedError

    def samples(self) -> List[Tuple[str, Tuple, float]]:
        """[(series_name, label_items, value)] for the exporters."""
        raise NotImplementedError


class Counter(Metric):
    """Monotonic counter.  The unlabeled path is one float add (hot-path
    safe); labeled children live in a dict keyed by sorted label items."""

    kind = "counter"

    def __init__(self, name, help="", registry=None):
        self._value = 0.0
        self._children: Dict[Tuple, float] = {}
        super().__init__(name, help, registry)

    def inc(self, value: float = 1.0, **labels) -> None:
        if labels:
            k = _label_key(labels)
            with _MUT_LOCK:
                self._children[k] = self._children.get(k, 0.0) + value
        else:
            with _MUT_LOCK:
                self._value += value

    @property
    def value(self) -> float:
        # list() snapshots in one GIL-atomic C copy: hook threads may
        # insert a new label key while we read
        return self._value + sum(list(self._children.values()))

    def get(self, **labels) -> float:
        return self._children.get(_label_key(labels), 0.0) if labels \
            else self._value

    def reset(self) -> None:
        self._value = 0.0
        self._children.clear()

    def fold_label(self, label: str, value, replacement) -> None:
        """Merge every child whose ``label`` equals ``value`` into the
        same label set with ``label=replacement`` — bounds label
        cardinality (e.g. evicted serving tenants fold into
        ``tenant="_evicted"``) while preserving the counter's total."""
        with _MUT_LOCK:
            for k in [k for k in list(self._children)
                      if dict(k).get(label) == value]:
                v = self._children.pop(k)
                d = dict(k)
                d[label] = replacement
                nk = _label_key(d)
                self._children[nk] = self._children.get(nk, 0.0) + v

    def samples(self):
        out = []
        if self._value or not self._children:
            out.append((self.name, (), self._value))
        for k, v in sorted(list(self._children.items())):
            out.append((self.name, k, v))
        return out


class Gauge(Metric):
    """Point-in-time value; optional callback makes it computed-on-read
    (used for HBM usage so device RPCs only happen at export time)."""

    kind = "gauge"

    def __init__(self, name, help="", registry=None, fn=None):
        self._value = 0.0
        self._children: Dict[Tuple, float] = {}
        self._fn = fn
        super().__init__(name, help, registry)

    def set(self, value: float, **labels) -> None:
        if labels:
            k = _label_key(labels)
            # same lock as replace_children(): a labeled set racing the
            # full-child-set swap must not land in the orphaned old dict
            # and vanish from every future export
            with _MUT_LOCK:
                self._children[k] = float(value)
        else:
            self._value = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        if labels:
            k = _label_key(labels)
            with _MUT_LOCK:
                self._children[k] = self._children.get(k, 0.0) + value
        else:
            with _MUT_LOCK:
                self._value += value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def get(self, **labels) -> float:
        if self._fn is not None and not labels:
            return float(self._fn())
        return self._children.get(_label_key(labels), 0.0) if labels \
            else self._value

    def remove(self, **labels) -> None:
        """Drop one labeled child (gauges are point-in-time, so removal
        is semantically clean — used to keep per-tenant gauge
        cardinality bounded when a tenant is evicted)."""
        with _MUT_LOCK:
            self._children.pop(_label_key(labels), None)

    def replace_children(self, items) -> None:
        """Atomically swap the FULL labeled-child set from an iterable
        of ``(labels_dict, value)`` — one reference assignment, so an
        export racing the rebuild sees either the old or the new
        complete set, never a half-built one (the export-time pull
        refresh idiom, e.g. the memory ledger's per-tag gauge)."""
        children = {_label_key(labels): float(v) for labels, v in items}
        with _MUT_LOCK:
            # same lock discipline as inc/dec/remove — a concurrent
            # labeled mutator must not land its write in the orphaned
            # old dict and vanish from every future export
            self._children = children

    def reset(self) -> None:
        self._value = 0.0
        self._children.clear()

    def samples(self):
        if self._fn is not None:
            try:
                out = [(self.name, (), float(self._fn()))]
            except Exception:
                out = [(self.name, (), 0.0)]
            # computed gauges may ALSO carry labeled children (the
            # per-mesh-axis MFU/flops splits refreshed by the fn pull)
            for k, v in sorted(list(self._children.items())):
                out.append((self.name, k, v))
            return out
        out = []
        if self._value or not self._children:
            out.append((self.name, (), self._value))
        for k, v in sorted(list(self._children.items())):
            out.append((self.name, k, v))
        return out


# default: latency-ish spread from 100us to ~100s
_DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0,
                    5.0, 10.0, 60.0)


class Histogram(Metric):
    """Fixed-bucket histogram (cumulative `le` buckets on export, like
    Prometheus); tracks sum + count so mean is recoverable.

    ``observe(value, exemplar=...)`` additionally remembers the latest
    exemplar (a flight-recorder trace_id) per bucket — the OpenMetrics
    exemplar idea: a p99 bucket links to one concrete recorded request
    timeline instead of an anonymous count (``exemplars()``,
    ``snapshot()["serving"]["latency_exemplars"]``)."""

    kind = "histogram"

    def __init__(self, name, help="", buckets=_DEFAULT_BUCKETS,
                 registry=None):
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._count = 0
        self._exemplars: Dict[int, Tuple[float, object]] = {}
        super().__init__(name, help, registry)

    def observe(self, value: float, exemplar=None) -> None:
        with _MUT_LOCK:
            self._sum += value
            self._count += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    if exemplar is not None:
                        self._exemplars[i] = (value, exemplar)
                    return
            self._counts[-1] += 1
            if exemplar is not None:
                self._exemplars[len(self.buckets)] = (value, exemplar)

    def exemplars(self) -> Dict[str, dict]:
        """{le: {"value", "trace_id"}} for buckets that have one —
        the hop from a latency percentile to `flight` dump spans."""
        with _MUT_LOCK:
            items = list(self._exemplars.items())
        out = {}
        for i, (v, ex) in sorted(items):
            le = "+Inf" if i >= len(self.buckets) \
                else repr(float(self.buckets[i]))
            out[le] = {"value": v, "trace_id": ex}
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def reset(self) -> None:
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._exemplars.clear()

    def samples(self):
        out, cum = [], 0
        for b, c in zip(self.buckets, self._counts):
            cum += c
            out.append((self.name + "_bucket", (("le", repr(float(b))),), cum))
        cum += self._counts[-1]
        out.append((self.name + "_bucket", (("le", "+Inf"),), cum))
        out.append((self.name + "_sum", (), self._sum))
        out.append((self.name + "_count", (), self._count))
        return out


class MetricsRegistry:
    """Name → Metric; collect/export/reset over the whole set."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = _make_lock("metrics.registry")

    def _register(self, metric: Metric) -> None:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def metrics(self) -> List[Metric]:
        return list(self._metrics.values())

    def reset(self) -> None:
        for m in self._metrics.values():
            m.reset()

    # -- exporters ----------------------------------------------------------
    def render_prometheus(self) -> str:
        lines = []
        for m in self._metrics.values():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for series, labels, value in m.samples():
                sel = ""
                if labels:
                    sel = "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"
                v = repr(float(value)) if isinstance(value, float) \
                    else str(value)
                lines.append(f"{series}{sel} {v}")
        return "\n".join(lines) + "\n"

    def render_json(self) -> str:
        return _json.dumps(self.to_dict(), sort_keys=True)

    def to_dict(self) -> dict:
        out = {}
        for m in self._metrics.values():
            if isinstance(m, Histogram):
                out[m.name] = {"type": "histogram", "sum": m.sum,
                               "count": m.count, "mean": m.mean,
                               "buckets": {repr(float(b)): c for b, c in
                                           zip(m.buckets, m._counts)},
                               "inf": m._counts[-1]}
            else:
                series = {}
                for name_, labels, value in m.samples():
                    key = ",".join(f"{k}={v}" for k, v in labels) or "_"
                    series[key] = value
                out[m.name] = {"type": m.kind, "values": series}
        return out


REGISTRY = MetricsRegistry()

# -- the runtime metric set ---------------------------------------------------
# Stable module-level objects: hooks reference these directly (no registry
# lookup on the hot path) and tests may assert identity stays put across
# enable/disable flips.
XLA_LAUNCHES = Counter(
    "mxnet_xla_launches_total",
    "Compiled XLA program launches by kind (fwd, bwd, fwd_bwd, fused_step, "
    "kvstore_merge, allreduce, optimizer, data)")
DEVICE_PUTS = Counter(
    "mxnet_device_put_total",
    "Explicit jax.device_put host->device / device->device transfers")
TRANSFER_BYTES = Counter(
    "mxnet_device_transfer_bytes_total",
    "Bytes moved by instrumented device transfers")
JIT_CACHE_HITS = Counter(
    "mxnet_jit_cache_hits_total",
    "Executor compiled-entry-point cache hits")
JIT_CACHE_MISSES = Counter(
    "mxnet_jit_cache_misses_total",
    "Executor compiled-entry-point cache misses (new jit closures)")
ENGINE_WAITS = Counter(
    "mxnet_engine_wait_total",
    "Engine blocking waits by kind (wait_for_var, wait_for_all)")
ENGINE_WAIT_SECONDS = Counter(
    "mxnet_engine_wait_seconds_total",
    "Seconds spent blocked in engine waits")
KVSTORE_PUSH_BYTES = Counter(
    "mxnet_kvstore_push_bytes_total",
    "Gradient bytes pushed into the kvstore")
KVSTORE_PULL_BYTES = Counter(
    "mxnet_kvstore_pull_bytes_total",
    "Parameter bytes pulled out of the kvstore")
KVSTORE_ALLREDUCE_SECONDS = Histogram(
    "mxnet_kvstore_allreduce_seconds",
    "Wall-clock latency of kvstore push/pushpull aggregation "
    "(includes cross-host allreduce when num_workers > 1)")
DATA_WAIT_SECONDS = Histogram(
    "mxnet_data_batch_wait_seconds",
    "Time the training loop waited for the next data batch")
OPTIMIZER_STEPS = Counter(
    "mxnet_optimizer_steps_total",
    "Optimizer step applications (fused multi-tensor update = 1)")
MONITOR_STATS = Counter(
    "mxnet_monitor_stats_total",
    "Executor monitor-callback stat records, by io direction")
FIT_STEP_DISPATCHES = Gauge(
    "mxnet_fit_step_dispatches",
    "XLA program launches + device_puts issued by the most recent "
    "steady-state Module.fit step, excluding async data-pipeline "
    "launches (the round-2 O(1)-dispatch invariant, now queryable)")
TRAINER_STEP_DISPATCHES = Gauge(
    "mxnet_trainer_step_dispatches",
    "XLA program launches + device_puts issued by the most recent "
    "gluon training step.  Fused path: Trainer.step's allreduce + "
    "optimizer (forward/backward are outside step() and counted under "
    "xla:fwd / xla:bwd).  Whole-step path (MXNET_WHOLE_STEP=1): the "
    "ENTIRE step — fwd+bwd+reduce+update ride one donated program "
    "(xla:whole_step), so this gauge reads 1")
SUPERSTEP_DISPATCHES = Gauge(
    "mxnet_superstep_dispatches",
    "XLA program launches + device_puts issued by the most recent "
    "superstep (K whole-steps lax.scan-compiled into one donated "
    "program, mxnet_tpu/autotune/superstep.py).  Scanned: 1 for the "
    "whole K-step superstep.  Reads ~K when the superstep silently "
    "demoted to K sequential whole-step dispatches — the perf "
    "sentinel's dispatches_per_step baseline for the 'superstep' "
    "phase trips on exactly that")
ALLREDUCE_BUCKETS = Gauge(
    "mxnet_allreduce_buckets",
    "Gradient buckets the most recent bucketed allreduce fused into "
    "(size-capped by MXNET_BUCKET_SIZE_MB; O(total grad bytes), "
    "independent of parameter count)")
PREFETCH_WAIT_SECONDS = Histogram(
    "mxnet_prefetch_wait_seconds",
    "Time the consumer blocked on the prefetch-to-device queue; near "
    "zero when the input pipeline keeps ahead of the device")
KVSTORE_WIRE_BYTES = Gauge(
    "mxnet_kvstore_wire_bytes",
    "PER-WORKER PAYLOAD bytes of the most recent compressed bucketed "
    "allreduce, by leg (intra = device-copy merge within a host, always "
    "full precision; dist = cross-host DCN) and stage (raw = what full "
    "precision would contribute, compressed = the packed 2-bit payload "
    "actually contributed, ~1/16 on float32).  NOTE: the compressed "
    "dist leg is an all-gather, so each worker RECEIVES "
    "(num_workers-1) x this payload — compare against a raw ring "
    "allreduce's ~2x raw bytes/worker when sizing pods (the 2-bit win "
    "holds up to ~32 workers)")
SERVE_REQUESTS = Counter(
    "mxnet_serve_requests_total",
    "Inference requests served by the serving fast path "
    "(mxnet_tpu.serving), coalesced or not")
SERVE_BATCHES = Counter(
    "mxnet_serve_batches_total",
    "Bucket dispatches issued by the serving fast path — one compiled "
    "XLA launch each; requests/batches is the coalescing factor")
SERVE_COMPILES = Counter(
    "mxnet_serve_compiles_total",
    "AOT bucket compiles (lower().compile()).  After warmup() this must "
    "stay FLAT under traffic — growth means requests are escaping the "
    "bucket set and paying hot-path compiles")
SERVE_QUEUE_DEPTH = Gauge(
    "mxnet_serve_queue_depth",
    "Requests waiting in the micro-batcher queue (sampled at "
    "submit/drain)")
SERVE_PADDING_WASTE = Gauge(
    "mxnet_serve_padding_waste",
    "Fraction of the most recent serving dispatch's input elements that "
    "were bucket padding (dead compute).  Persistently high means the "
    "bucket ladder is too coarse for the traffic: widen "
    "MXNET_SERVE_BUCKETS")
SERVE_COALESCED_ROWS = Gauge(
    "mxnet_serve_coalesced_rows",
    "Rows in the most recent coalesced micro-batch (before bucket "
    "padding)")
SERVE_LATENCY_SECONDS = Histogram(
    "mxnet_serve_request_seconds",
    "End-to-end request latency through the serving fast path (includes "
    "micro-batcher queue wait on the coalesced path)",
    buckets=(1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
             5e-2, 0.1, 0.25, 1.0, 5.0))
SERVE_ADMITTED = Counter(
    "mxnet_serve_admitted_total",
    "Requests admitted past ResilientServer admission control, by "
    "tenant (shed requests never count here)")
SERVE_SHED = Counter(
    "mxnet_serve_shed_total",
    "Requests rejected by admission control with a typed Overloaded "
    "error, by tenant and reason (queue_full = per-tenant bound hit, "
    "deadline_unmeetable = estimated wait already exceeds the request's "
    "deadline).  Shedding here is the DESIGN under overload: bounded "
    "p99 + rejections instead of tail-latency collapse")
SERVE_EXPIRED = Counter(
    "mxnet_serve_expired_total",
    "Admitted requests dropped before dispatch because their deadline "
    "passed in queue (typed DeadlineExceeded to the caller; expired "
    "work is NEVER padded or dispatched), by tenant")
SERVE_GOODPUT = Gauge(
    "mxnet_serve_goodput",
    "served / admitted fraction per tenant since process start — the "
    "overload acceptance gauge (>= 0.9 of admitted work must complete "
    "under 2x flood; shed requests are excluded by construction)")
SERVE_READY = Gauge(
    "mxnet_serve_ready",
    "1 when the most recently evaluated ResilientServer readyz() "
    "passes (warmup complete, dispatch latency / failure rate / stall "
    "/ hot-reload staleness within thresholds), else 0")
SERVE_READY_TRANSITIONS = Counter(
    "mxnet_serve_ready_transitions_total",
    "readyz flips, by direction (up = became ready, down = became "
    "unready).  A flapping counter is the page-the-oncall signal that "
    "the replica is oscillating around a threshold")
SERVE_EVICTIONS = Counter(
    "mxnet_serve_evictions_total",
    "LRU evictions by the multi-model HBM budgeter (serving."
    "ModelRegistry), by kind (bucket = one AOT executable + its zero "
    "placeholders dropped, model = device weights dropped too — host "
    "param payload kept for restart-free readmission) and model.  "
    "Eviction churn under a tight MXNET_HBM_BUDGET_MB is the DESIGN: "
    "the k+1'th model degrades by policy instead of OOMing the process "
    "(docs/multi_model.md)")
SERVE_READMITS = Counter(
    "mxnet_serve_readmissions_total",
    "Readmissions of evicted serving state, by kind (model = weights "
    "re-uploaded from the host payload, bucket = an evicted bucket's "
    "executable rebuilt — a persistent-compile-cache hit when "
    "MXNET_COMPILE_CACHE_DIR is wired, so it never counts against the "
    "stay-flat SERVE_COMPILES contract).  readmissions/evictions is "
    "the churn ratio: high means the budget is too tight for the "
    "working set")
SERVE_RESIDENT_MODELS = Gauge(
    "mxnet_serve_resident_models",
    "Registered serving models whose device weights are currently "
    "resident (ModelRegistry; total registered minus weights-evicted).  "
    "Bounded by MXNET_SERVE_MAX_MODELS")
SERVE_MODEL_HBM_BYTES = Gauge(
    "mxnet_serve_model_hbm_bytes",
    "Tracked device bytes per registered serving model (its served "
    "weights + bucket placeholders; 0 while weights-evicted), by model "
    "label — the bounded per-model slice of the process-wide "
    "serve_weights ledger tag, refreshed on every eviction/readmission "
    "and at snapshot()")
SERVE_RELOAD_FAILURES = Counter(
    "mxnet_serve_reload_failures_total",
    "Serving auto-reload poll failures (missing/corrupt checkpoint "
    "dir, failed weight swap).  Each one kept serving the OLD weights; "
    "a climbing counter means the training->serving pipeline is broken "
    "while the replica still looks healthy")
DECODE_STEPS = Counter(
    "mxnet_decode_steps_total",
    "Continuous-batching decode steps (serving.DecodeEngine) — each is "
    "exactly ONE donated XLA dispatch over the whole in-flight slot "
    "set; compare against dispatch_counts()['decode'] to catch a step "
    "that silently multi-dispatched")
DECODE_TOKENS = Counter(
    "mxnet_decode_tokens_total",
    "Tokens generated by continuous-batching decode (prompt-consuming "
    "steps excluded)")
DECODE_KV_EVICTIONS = Counter(
    "mxnet_decode_kv_evictions_total",
    "Sequences whose paged KV state was reclaimed under HBM pressure "
    "(typed SequenceEvicted with retry-after to the caller).  KV pages "
    "are the CHEAPEST victims in the multi-model eviction ladder — "
    "churn here under a tight MXNET_HBM_BUDGET_MB is the design, a "
    "generative tenant bending before any classifier's weights do")
DECODE_INFLIGHT = Gauge(
    "mxnet_decode_inflight_sequences",
    "Sequences currently holding a decode slot (joined, not yet "
    "finished/retired) — refreshed every decode step")
DECODE_KV_OCCUPANCY = Gauge(
    "mxnet_decode_kv_page_occupancy",
    "Fraction of the currently-routed KV page lattice key's token "
    "capacity holding live sequence state.  Persistently low means the "
    "lattice is over-provisioned for the traffic (shrink "
    "MXNET_DECODE_SLOTS / MXNET_DECODE_MAX_PAGES)")
DECODE_TOKENS_PER_S = Gauge(
    "mxnet_decode_tokens_per_second",
    "Instantaneous decode throughput: active sequences advanced by the "
    "most recent step / its wall-clock (continuous batching's win over "
    "request-level coalescing is exactly this gauge under mixed-length "
    "traffic — the bench.py decode rider pins it)")
FAULTS_INJECTED = Counter(
    "mxnet_faults_injected_total",
    "Faults fired by the mxnet_tpu.faultinject harness, by site and "
    "mode.  Nonzero in production means someone left MXNET_FAULT_PLAN "
    "set")
CHECKPOINT_SAVE_SECONDS = Histogram(
    "mxnet_checkpoint_save_seconds",
    "Full wall-clock of each checkpoint save, snapshot through atomic "
    "commit (async saves: measured on the writer thread)")
CHECKPOINT_SAVE_BLOCKED_SECONDS = Histogram(
    "mxnet_checkpoint_save_blocked_seconds",
    "Time CheckpointManager.save() blocked its caller — the step "
    "critical-path cost.  Async mode: just the device->host snapshot; "
    "sync mode: the whole write")
CHECKPOINT_RESTORE_SECONDS = Histogram(
    "mxnet_checkpoint_restore_seconds",
    "Wall-clock of each successful checkpoint restore (CRC validation "
    "included)")
CHECKPOINT_BYTES_WRITTEN = Counter(
    "mxnet_checkpoint_bytes_written_total",
    "Payload bytes committed by checkpoint saves (shard files)")
CHECKPOINT_LAST_STEP = Gauge(
    "mxnet_checkpoint_last_step",
    "Step of the most recent successfully committed checkpoint — a "
    "flat-lining value under traffic is the page-the-oncall signal "
    "that durable state has stopped advancing")
CHECKPOINT_FAILURES = Counter(
    "mxnet_checkpoint_failures_total",
    "Checkpoint subsystem failures by stage (save_attempt = retried "
    "transient IO error, save = retries exhausted, restore = torn/"
    "corrupt checkpoint skipped, gc = retention sweep error) and "
    "reason")
ANALYSIS_LOCK_VIOLATIONS = Counter(
    "mxnet_analysis_lock_order_violations_total",
    "Concurrency-sanitizer lock findings under MXNET_SANITIZE=1, by "
    "kind (cycle = ABBA ordering hazard across subsystem locks, "
    "reentry = same-thread re-acquisition of a non-reentrant lock — "
    "the PR 5 SIGTERM-mid-save deadlock class).  Nonzero anywhere, "
    "including chaos runs, is a bug")
ANALYSIS_SYNC_VIOLATIONS = Counter(
    "mxnet_analysis_sync_violations_total",
    "Device->host syncs observed inside analysis.no_sync() regions "
    "(runtime complement of the static host-sync graft-lint rule)")
FLIGHT_DUMPS = Counter(
    "mxnet_flight_dumps_total",
    "Flight-recorder timeline dumps by reason (manual = flight.dump() "
    "call, anomaly = slow-phase watchdog trip [k x EWMA, "
    "MXNET_FLIGHT_SLOW_FACTOR], signal = SIGUSR2, oom = device "
    "RESOURCE_EXHAUSTED post-mortem via memory.oom_guard).  A climbing "
    "anomaly "
    "count is the page-the-oncall signal that steps/requests keep "
    "blowing their own baseline — each dump under MXNET_FLIGHT_DIR "
    "holds the timeline of the moments before it")
MEMORY_LEDGER_BYTES = Gauge(
    "mxnet_memory_ledger_bytes",
    "Tracked live bytes by ledger tag and space (mxnet_tpu."
    "observability.memory; bounded tag set — param/grad/output/executor/"
    "optimizer_state/grad_bucket/compression_residual/serve_weights/"
    "kvstore/prefetch/data/checkpoint_host/serve_host_params, "
    "space=device|host [host = e.g. checkpoint snapshot twins and the "
    "serve_host_params readmission payload evicted serving models "
    "reload from], and "
    "_untagged for the unattributed remainder).  Bytes are LOGICAL "
    "(global) array bytes; on a GSPMD mesh memory.report() breaks each "
    "buffer into per-shard bytes (shard_bytes / spec fields) and "
    "per-tag shard totals — the per-device HBM cost, NOT the "
    "replicated sum.  Refreshed at export "
    "time from the weakref ledger, never on the hot path")
SERVE_BUCKET_HBM_BYTES = Gauge(
    "mxnet_serve_bucket_hbm_bytes",
    "Compiled peak HBM bytes per serving bucket (CompiledMemoryStats "
    "of the AOT executable, set once at precompile; labels are the "
    "bounded bucket-lattice set).  The multi-model HBM budgeter's "
    "per-bucket cost table — what an LRU bucket eviction would free")
FUSED_DTYPE_RECOMPILES = Counter(
    "mxnet_fused_dtype_policy_recompiles_total",
    "Compiled-step program recompiles caused by a dtype-policy "
    "(MXNET_AMP) change, by step mode (update_all / whole_step).  Each "
    "is deliberate and LOUD (FusedUpdater.lookup_program logs it): the "
    "alternative — silently reusing a program traced under another "
    "precision for bf16/fp16 gradients — would train in the wrong "
    "dtype without ever erroring.  A count that climbs every step "
    "means something is flapping MXNET_AMP mid-run")
SUPERVISOR_SNAPSHOTS = Counter(
    "mxnet_supervisor_snapshots_total",
    "Rolling host snapshots the TrainingSupervisor took (every "
    "MXNET_SUPERVISE_SNAPSHOT_STEPS) — the donation-safe restore points "
    "transient-step retries rebuild from")
SUPERVISOR_RETRIES = Counter(
    "mxnet_supervisor_retries_total",
    "Supervised training steps re-executed after a transient failure "
    "(restore last snapshot -> replay window -> retry).  A climbing "
    "count with training still progressing is the supervisor doing its "
    "job; pair with faults_injected to tell chaos from real faults")
SUPERVISOR_REWINDS = Counter(
    "mxnet_supervisor_rewinds_total",
    "Snapshot restores performed by the TrainingSupervisor, by reason "
    "(retry = transient-step recovery, divergence = "
    "MXNET_SUPERVISE_ON_DIVERGE=rewind)")
SUPERVISOR_WATCHDOG_TRIPS = Counter(
    "mxnet_supervisor_watchdog_trips_total",
    "Training watchdog firings by kind (divergence = "
    "MXNET_SUPERVISE_DIVERGE_PATIENCE consecutive nonfinite losses, "
    "stall = a step blew its EWMA-derived deadline).  Each trip leaves "
    "one rate-limited post-mortem (report + flight ring) under "
    "MXNET_FLIGHT_DIR")
SUPERVISOR_LAST_SNAPSHOT_STEP = Gauge(
    "mxnet_supervisor_last_snapshot_step",
    "Step id of the TrainingSupervisor's most recent rolling host "
    "snapshot — how far back a donation-safe retry would rewind")
PREFETCH_RESPAWNS = Counter(
    "mxnet_prefetch_respawns_total",
    "AsyncPrefetcher worker threads respawned after a transient IO "
    "error (one respawn per prefetcher lifetime; a second transient "
    "surfaces to the consumer)")
DATA_RECORDS_SKIPPED = Counter(
    "mxnet_data_records_skipped_total",
    "Corrupt input records skipped by the prefetcher's "
    "MXNET_DATA_SKIP_BUDGET (typed DataSkipBudgetError on exhaustion)")
COMPRESSION_ERROR = Histogram(
    "mxnet_compression_error",
    "Mean |quantization error| per gradient bucket per compressed "
    "allreduce (the error-feedback residual magnitude; bounded by the "
    "2-bit threshold).  Growing means the threshold is too coarse for "
    "the gradient scale",
    buckets=(1e-4, 1e-3, 1e-2, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0))
PERF_REGRESSIONS = Counter(
    "mxnet_perf_regressions_total",
    "Perf-regression sentinel firings (mxnet_tpu.observability."
    "introspect), by kind (step_time = warmed step-time EWMA blew the "
    "persisted baseline p50 by REGRESSION_FACTOR, dispatches = "
    "steady-state dispatches/step grew past the baseline) and phase "
    "(whole_step / trainer_step).  Each firing is rate-limited to once "
    "per regression episode; the regression also fails the "
    "perf_regression readyz() check until it clears or the baseline is "
    "refreshed (docs/introspection.md)")


def _goodput_ratio() -> float:
    """Export-time pull of the goodput fraction from the run ledger
    (lazy/guarded — a scrape must never fail because of it; 0.0 until
    any span is attributed)."""
    try:
        from . import goodput as _gp
        if not _gp.ENABLED:
            return 0.0
        return float(_gp.ratio())
    except Exception:  # noqa: BLE001
        return 0.0


GOODPUT_RATIO = Gauge(
    "mxnet_goodput_ratio",
    "Fraction (0..1) of this run's wall-clock attributed to useful "
    "compute (flight trainer_step/whole_step/serve_dispatch spans) by "
    "the goodput ledger (mxnet_tpu.observability.goodput) — the rest "
    "is badput (mxnet_badput_seconds_total) or unattributed.  Computed "
    "at export; docs/goodput.md",
    fn=lambda: _goodput_ratio())
BADPUT_SECONDS = Counter(
    "mxnet_badput_seconds_total",
    "Wall-clock seconds lost to each badput class, by reason "
    "(data_wait / checkpoint_block / retry_replay / rewind / recompile "
    "/ eviction_churn / stall / shed — the closed goodput taxonomy; "
    "docs/goodput.md)")
SLO_BURN = Counter(
    "mxnet_slo_burn_total",
    "Rate-limited SLO burn firings, by slo (goodput = run goodput %% "
    "fell below MXNET_SLO_GOODPUT_PCT, serve_p99 = sliding-window "
    "serve p99 exceeded MXNET_SLO_SERVE_P99_MS).  Each firing also "
    "warns, journals an slo_burn entry, and fails the slo_burn "
    "readyz() check until the window recovers (docs/goodput.md)")


def _introspect_mfu(key: str) -> float:
    """Export-time pull of one MFU/roofline field from the introspect
    layer (lazy/guarded — a scrape must never fail because of it;
    0.0 until both a program capture and a warmed step EWMA exist).
    The "mfu" pull also refreshes the per-mesh-axis children: on a
    GSPMD mesh the MFU gauge gains a {mesh=<signature>} child and the
    flops gauge per-axis {mesh_axis=...} splits (the sharded run's
    flops divided by each axis size)."""
    try:
        from . import introspect as _int
        if not _int.ENABLED:
            return 0.0
        d = _int.mfu()
        if key == "mfu":
            msig = d.get("mesh")
            MFU.replace_children(
                [({"mesh": msig}, float(d.get("mfu") or 0.0))]
                if msig else [])
            STEP_FLOPS_PER_S.replace_children(
                [({"mesh_axis": a}, float(v)) for a, v in
                 sorted((d.get("per_axis_flops_per_s") or {}).items())])
        return float(d.get(key) or 0.0)
    except Exception:  # noqa: BLE001
        return 0.0


MFU = Gauge(
    "mxnet_mfu",
    "Model flops utilization of the training step, 0..1: analytical "
    "flops/step of the captured step program(s) / the flight "
    "recorder's warmed step-time EWMA / platform peak flops "
    "(MXNET_PEAK_FLOPS override; the CPU default peak is a nominal "
    "placeholder).  On a GSPMD mesh a {mesh=<axis=size,...>} child "
    "carries the same value keyed by mesh shape so dashboards can "
    "group sharded vs replicated runs.  Computed at export only",
    fn=lambda: _introspect_mfu("mfu"))
STEP_FLOPS_PER_S = Gauge(
    "mxnet_step_flops_per_s",
    "Achieved flops/s of the training step (analytical flops/step / "
    "warmed step-time EWMA) — the roofline y-axis.  On a GSPMD mesh, "
    "per-mesh-axis {mesh_axis=batch|model|...} children split the "
    "total by axis size (the per-shard share along each axis).  "
    "Computed at export",
    fn=lambda: _introspect_mfu("flops_per_s"))
STEP_BYTES_PER_S = Gauge(
    "mxnet_step_bytes_per_s",
    "Achieved HBM bytes/s of the training step (cost_analysis bytes "
    "accessed / warmed step-time EWMA).  Computed at export",
    fn=lambda: _introspect_mfu("bytes_per_s"))
STEP_ARITH_INTENSITY = Gauge(
    "mxnet_step_arithmetic_intensity",
    "Analytical flops per byte accessed of the training step — the "
    "roofline x-axis (compare against the platform's ridge point to "
    "see compute- vs memory-bound).  Computed at export",
    fn=lambda: _introspect_mfu("arithmetic_intensity"))


def _hbm_stats_all() -> List[dict]:
    """Per-device memory_stats() — TPU backends report bytes_in_use /
    peak_bytes_in_use / bytes_limit; CPU returns nothing."""
    out = []
    try:
        import jax
        for d in jax.local_devices():
            try:
                s = d.memory_stats()
            except Exception:
                s = None
            if s:
                out.append({"device": str(d.id), "platform": d.platform,
                            **{k: v for k, v in s.items()
                               if isinstance(v, (int, float))}})
    except Exception:
        pass
    return out


def hbm_stats() -> List[dict]:
    return _hbm_stats_all()


def _hbm_in_use_total() -> float:
    return float(sum(s.get("bytes_in_use", 0) for s in _hbm_stats_all()))


HBM_BYTES_IN_USE = Gauge(
    "mxnet_hbm_bytes_in_use",
    "Sum of bytes_in_use over jax.local_devices() (sampled at export)",
    fn=_hbm_in_use_total)


# -- product API --------------------------------------------------------------
def step_dispatches() -> float:
    """Launch + transfer tally EXCLUDING kind=\"data\" launches — the
    windowed delta `Module.fit` publishes as FIT_STEP_DISPATCHES.  Data
    launches are excluded because a PrefetchingIter producer thread
    issues them mid-step, which would make the per-step delta
    nondeterministic."""
    return (XLA_LAUNCHES.value - XLA_LAUNCHES.get(kind="data")
            + DEVICE_PUTS.value)


def dispatch_counts() -> Dict[str, float]:
    """Per-kind dispatch tally since process start (or the last
    `REGISTRY.reset()`): compiled-program launches keyed `xla:<kind>`
    plus `device_put`.  The per-step delta of this dict is the invariant
    `tests/test_dispatch_count.py` pins; `fit_step_dispatches` (a gauge,
    also in `snapshot()`) carries the most recent fit step's total."""
    out: Dict[str, float] = {}
    # list() snapshots the dict in one C-level copy (GIL-atomic) so a
    # producer thread inserting a new label kind mid-call cannot raise
    # "dictionary changed size during iteration"
    for labels, v in list(XLA_LAUNCHES._children.items()):
        kind = dict(labels).get("kind", "other")
        out["xla:" + kind] = out.get("xla:" + kind, 0.0) + v
    if XLA_LAUNCHES._value:
        out["xla:other"] = out.get("xla:other", 0.0) + XLA_LAUNCHES._value
    out["device_put"] = DEVICE_PUTS.value
    out["total"] = XLA_LAUNCHES.value + DEVICE_PUTS.value
    return out


def _sum_by_label(counter: Counter, label: str) -> Dict[str, float]:
    """Aggregate a labeled counter's children over one label (the
    snapshot()-friendly marginal, e.g. evictions by kind summed over
    models).  list() snapshots against concurrent label inserts."""
    out: Dict[str, float] = {}
    for k, v in list(counter._children.items()):
        key = dict(k).get(label, "_")
        out[key] = out.get(key, 0.0) + v
    return out


def _flight_snapshot() -> dict:
    """snapshot()["flight"]: ring/watchdog state + per-phase p50/p99 +
    slowest-record exemplars (docs/observability.md).  Lazy/guarded —
    the metrics layer must never fail because of the recorder."""
    try:
        from . import flight as _fl
        return _fl.snapshot_summary()
    except Exception:  # noqa: BLE001
        return {"enabled": False}


def _memory_snapshot() -> dict:
    """snapshot()["memory"]: per-tag live/peak bytes, attribution pct,
    untagged remainder, budget + OOM state (docs/memory.md).  Lazy/
    guarded — the metrics layer must never fail because of the
    ledger."""
    try:
        from . import memory as _mem
        return _mem.snapshot_summary()
    except Exception:  # noqa: BLE001
        return {"enabled": False}


def _programs_snapshot() -> dict:
    """snapshot()["programs"]: per-program flops/bytes/peak + MFU +
    perf-sentinel state (docs/introspection.md).  Lazy/guarded — the
    metrics layer must never fail because of the introspector."""
    try:
        from . import introspect as _int
        return _int.snapshot_summary()
    except Exception:  # noqa: BLE001
        return {"enabled": False}


def _goodput_snapshot() -> dict:
    """snapshot()["goodput"]: per-class seconds/events, goodput %,
    unattributed slack, SLO targets + burn state, and the active run
    journal id/path (docs/goodput.md).  Lazy/guarded — the metrics
    layer must never fail because of the ledger."""
    try:
        from . import goodput as _gp
        out = _gp.report()
        if out.get("enabled"):
            out["slo"] = _gp.slo_state()
        from . import journal as _jr
        out["run_id"] = _jr.run_id()
        out["journal_path"] = _jr.path()
        return out
    except Exception:  # noqa: BLE001
        return {"enabled": False}


def _analysis_snapshot() -> dict:
    """snapshot()["analysis"]: sanitizer state + violation counters
    (docs/static_analysis.md).  The sanitizer import is lazy/guarded —
    the metrics layer must never fail because of it."""
    out = {"lock_order_violations": ANALYSIS_LOCK_VIOLATIONS.value,
           "sync_violations": ANALYSIS_SYNC_VIOLATIONS.value}
    try:
        from ..analysis import sanitizer as _san
        out.update(_san.state())
    except Exception:  # noqa: BLE001
        out["enabled"] = False
    return out


def snapshot() -> dict:
    """One JSON-able dict with the numbers a perf PR needs: dispatch
    accounting, transfer volume, data-wait, engine stalls, HBM."""
    return {
        "dispatch_counts": dispatch_counts(),
        "fit_step_dispatches": FIT_STEP_DISPATCHES.get(),
        "trainer_step_dispatches": TRAINER_STEP_DISPATCHES.get(),
        "superstep_dispatches": SUPERSTEP_DISPATCHES.get(),
        "allreduce_buckets": ALLREDUCE_BUCKETS.get(),
        "prefetch_wait_ms_total": PREFETCH_WAIT_SECONDS.sum * 1e3,
        "transfer_bytes": TRANSFER_BYTES.value,
        "kvstore_push_bytes": KVSTORE_PUSH_BYTES.value,
        "kvstore_pull_bytes": KVSTORE_PULL_BYTES.value,
        "kvstore_wire_bytes": {
            "dist_raw": KVSTORE_WIRE_BYTES.get(leg="dist", stage="raw"),
            "dist_compressed": KVSTORE_WIRE_BYTES.get(
                leg="dist", stage="compressed"),
            "intra_raw": KVSTORE_WIRE_BYTES.get(leg="intra", stage="raw"),
        },
        "compression_error_mean": COMPRESSION_ERROR.mean,
        "data_wait_ms_total": DATA_WAIT_SECONDS.sum * 1e3,
        "data_wait_ms_mean": DATA_WAIT_SECONDS.mean * 1e3,
        "engine_wait_seconds": ENGINE_WAIT_SECONDS.value,
        "jit_cache": {"hits": JIT_CACHE_HITS.value,
                      "misses": JIT_CACHE_MISSES.value},
        "optimizer_steps": OPTIMIZER_STEPS.value,
        "fused_dtype_recompiles": FUSED_DTYPE_RECOMPILES.value,
        "serving": {
            "requests": SERVE_REQUESTS.value,
            "batches": SERVE_BATCHES.value,
            "compiles": SERVE_COMPILES.value,
            "queue_depth": SERVE_QUEUE_DEPTH.get(),
            "padding_waste": SERVE_PADDING_WASTE.get(),
            "coalesced_rows": SERVE_COALESCED_ROWS.get(),
            "latency_ms_mean": SERVE_LATENCY_SECONDS.mean * 1e3,
            "admitted": SERVE_ADMITTED.value,
            "shed": SERVE_SHED.value,
            "expired": SERVE_EXPIRED.value,
            # list() snapshots against hook threads inserting tenants
            "goodput": {dict(k).get("tenant", "_"): v for k, v in
                        sorted(list(SERVE_GOODPUT._children.items()))},
            "ready": SERVE_READY.get(),
            "ready_transitions": SERVE_READY_TRANSITIONS.value,
            "reload_failures": SERVE_RELOAD_FAILURES.value,
            "faults_injected": FAULTS_INJECTED.value,
            # multi-model registry (docs/multi_model.md): eviction
            # churn by kind, the resident-model gauge, and the
            # per-model HBM slice — list() snapshots against the
            # registry mutating label sets mid-export
            "evictions": _sum_by_label(SERVE_EVICTIONS, "kind"),
            "readmissions": SERVE_READMITS.value,
            "resident_models": SERVE_RESIDENT_MODELS.get(),
            "model_hbm_bytes": {
                dict(k).get("model", "_"): v for k, v in
                sorted(list(SERVE_MODEL_HBM_BYTES._children.items()))},
            # exemplar hop: p99 bucket -> trace_id -> flight dump spans
            "latency_exemplars": SERVE_LATENCY_SECONDS.exemplars(),
            # continuous-batching decode (docs/decode_serving.md):
            # steps == dispatch_counts()['decode'] is the 1-dispatch
            # contract; kv_evictions is the budget arbiter choosing
            # pages over weights
            "decode": {
                "steps": DECODE_STEPS.value,
                "tokens": DECODE_TOKENS.value,
                "inflight": DECODE_INFLIGHT.get(),
                "kv_page_occupancy": DECODE_KV_OCCUPANCY.get(),
                "tokens_per_s": DECODE_TOKENS_PER_S.get(),
                "kv_evictions": DECODE_KV_EVICTIONS.value,
            },
        },
        "flight": _flight_snapshot(),
        "goodput": _goodput_snapshot(),
        "memory": _memory_snapshot(),
        "programs": _programs_snapshot(),
        "analysis": _analysis_snapshot(),
        "supervisor": {
            "snapshots": SUPERVISOR_SNAPSHOTS.value,
            "last_snapshot_step": SUPERVISOR_LAST_SNAPSHOT_STEP.get(),
            "retries": SUPERVISOR_RETRIES.value,
            "rewinds": {dict(k).get("reason", "_"): v for k, v in
                        sorted(list(SUPERVISOR_REWINDS._children.items()))},
            "watchdog_trips": {
                dict(k).get("kind", "_"): v for k, v in
                sorted(list(SUPERVISOR_WATCHDOG_TRIPS._children.items()))},
            "prefetch_respawns": PREFETCH_RESPAWNS.value,
            "data_records_skipped": DATA_RECORDS_SKIPPED.value,
        },
        "checkpoint": {
            "last_step": CHECKPOINT_LAST_STEP.get(),
            "saves": CHECKPOINT_SAVE_SECONDS.count,
            "save_ms_mean": CHECKPOINT_SAVE_SECONDS.mean * 1e3,
            "save_blocked_ms_mean":
                CHECKPOINT_SAVE_BLOCKED_SECONDS.mean * 1e3,
            "restores": CHECKPOINT_RESTORE_SECONDS.count,
            "restore_ms_mean": CHECKPOINT_RESTORE_SECONDS.mean * 1e3,
            "bytes_written": CHECKPOINT_BYTES_WRITTEN.value,
            "failures": CHECKPOINT_FAILURES.value,
        },
        "hbm": hbm_stats(),
    }


def _refresh_export_gauges() -> None:
    """Pull-style gauges that aren't ``fn=``-driven refresh here so the
    render paths export fresh values even when ``snapshot()`` never
    runs (the documented Prometheus scrape wiring).  Lazy/guarded — a
    render must never fail because of the ledger."""
    try:
        from . import memory as _mem
        _mem.refresh_gauge()
    except Exception:  # noqa: BLE001
        pass


def render_prometheus() -> str:
    _refresh_export_gauges()
    return REGISTRY.render_prometheus()


def render_json() -> str:
    _refresh_export_gauges()
    return REGISTRY.render_json()
