"""Timeline export for the flight recorder: Chrome trace-event JSON
(Perfetto-loadable) + per-phase latency digests.

One format, three producers merged on one timeline:

  * flight-recorder ring records (``flight.records()``) — training and
    serving phase spans, per-thread tids, step/trace_id args;
  * the profiler's python-side ``_events`` (eager op invokes and
    ``trace_span`` scopes) — already Chrome-trace complete events;
  * (device-side detail stays in the xplane trace directory the
    profiler manages; wall-clock lines the two files up in Perfetto.)

All python-side producers stamp ``time.perf_counter()`` microseconds,
so sorting by ``ts`` is globally consistent.  The dump is the standard
`trace-event format <https://docs.google.com/document/d/1CvAClvFfyA5R-
PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_: load it in Perfetto
(ui.perfetto.dev) or chrome://tracing unmodified.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["build_trace", "chrome_events", "summarize"]

#: pid stamped on every python-side event — matches profiler._events so
#: all sources group under one process row in the viewer
PID = 0


def _phase_flops() -> Dict[str, float]:
    """{step phase: analytical flops/step} from the program
    introspector — the feed for the ``mxnet_flops_per_s`` counter
    track.  Lazy/guarded: the exporter must never fail because of it."""
    try:
        from . import introspect as _int
        return _int.phase_flops_map() if _int.ENABLED else {}
    except Exception:  # noqa: BLE001
        return {}


def _badput_map() -> Dict[str, str]:
    """{span name: badput class} from the goodput ledger's taxonomy —
    the feed for the ``mxnet_badput_seconds`` counter track.
    Lazy/guarded: the exporter must never fail because of it."""
    try:
        from . import goodput as _gp
        if not _gp.ENABLED:
            return {}
        return {n: c for n, c in _gp._SPAN_CLASS.items()
                if c != "compute"}
    except Exception:  # noqa: BLE001
        return {}


def chrome_events(flight_records: List[tuple]) -> List[dict]:
    """``(segment, record)`` pairs → Chrome trace complete events plus
    one thread_name metadata event per segment."""
    events: List[dict] = []
    seen_tids: Dict[int, str] = {}
    phase_flops = _phase_flops()
    badput_map = _badput_map()
    badput_cum: Dict[str, float] = {}
    # cumulative badput must grow monotonically along the timeline, so
    # the counter walks records in span-end order regardless of which
    # thread segment recorded them
    for _, rec in sorted(flight_records, key=lambda p: p[1][3]):
        name, _, t0, t1, _, _, _ = rec
        cls = badput_map.get(name)
        if cls is None or t1 <= t0:
            continue
        badput_cum[cls] = badput_cum.get(cls, 0.0) + (t1 - t0) / 1e6
        # one "mxnet_badput_seconds" track per class: Perfetto renders
        # stacked cumulative badput lined up with the spans that caused
        # it (docs/goodput.md)
        events.append({"name": "mxnet_badput_seconds", "ph": "C",
                       "ts": t1, "pid": PID,
                       "args": {cls: round(badput_cum[cls], 6)}})
    for seg, rec in flight_records:
        name, cat, t0, t1, step, trace_id, labels = rec
        seen_tids.setdefault(seg.tid, seg.thread_name)
        ev = {"name": name, "cat": cat, "ph": "X", "ts": t0,
              "dur": t1 - t0, "pid": PID, "tid": seg.tid}
        args = {}
        if step is not None:
            args["step"] = step
        if trace_id is not None:
            args["trace_id"] = trace_id
        if labels:
            args.update(labels)
        if args:
            ev["args"] = args
        events.append(ev)
        if labels and "mem_live_bytes" in labels:
            # ledger-sampled phases also emit a Chrome COUNTER event at
            # phase end: Perfetto renders one "hbm_live_bytes" track
            # whose steps line up with the phase spans — the
            # which-phase-grew-HBM view (docs/memory.md)
            events.append({"name": "hbm_live_bytes", "ph": "C",
                           "ts": t1, "pid": PID,
                           "args": {"bytes": labels["mem_live_bytes"]}})
        if name in phase_flops and t1 > t0:
            # step phases with a captured program get an achieved-
            # flops/s counter track: analytical flops/step over the
            # span's measured duration — the roofline view lined up
            # with the phase spans (docs/introspection.md)
            events.append({"name": "mxnet_flops_per_s", "ph": "C",
                           "ts": t1, "pid": PID,
                           "args": {"flops_per_s":
                                    phase_flops[name] * 1e6 / (t1 - t0)}})
    for tid, tname in sorted(seen_tids.items()):
        events.append({"name": "thread_name", "ph": "M", "pid": PID,
                       "tid": tid, "args": {"name": tname}})
    return events


def build_trace(flight_records: List[tuple],
                profiler_events: Optional[List[dict]] = None,
                meta: Optional[dict] = None) -> dict:
    """The full dump payload: flight events merged with the profiler's
    ``_events`` (same pid/clock), sorted by timestamp so viewers and
    tests see one coherent timeline."""
    events = chrome_events(flight_records)
    if profiler_events:
        events.extend(profiler_events)
    events.sort(key=lambda e: e.get("ts", 0))
    out = {"traceEvents": events, "displayTimeUnit": "ms"}
    if meta:
        out["metadata"] = dict(meta)
    return out


def _pctl(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def summarize(flight_records: List[tuple], top: int = 3) -> dict:
    """Per-phase digest: ``{name: {count, total_ms, p50_ms, p99_ms,
    max_ms, slowest: [{dur_ms, t0_us, step, trace_id}]}}`` — the
    compact complement of the full dump (``snapshot()["flight"]``).
    ``slowest`` carries step/trace_id so a bad percentile links to a
    concrete recorded timeline."""
    by_name: Dict[str, List[tuple]] = {}
    for _, rec in flight_records:
        by_name.setdefault(rec[0], []).append(rec)
    out: Dict[str, dict] = {}
    for name, recs in sorted(by_name.items()):
        durs = sorted(r[3] - r[2] for r in recs)   # microseconds
        slowest = sorted(recs, key=lambda r: r[3] - r[2],
                         reverse=True)[:max(0, top)]
        out[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs) / 1e3, 3),
            "p50_ms": round(_pctl(durs, 0.50) / 1e3, 3),
            "p99_ms": round(_pctl(durs, 0.99) / 1e3, 3),
            "max_ms": round(durs[-1] / 1e3, 3),
            "slowest": [{"dur_ms": round((r[3] - r[2]) / 1e3, 3),
                         "t0_us": round(r[2], 1),
                         "step": r[4], "trace_id": r[5]}
                        for r in slowest],
        }
    return out
