"""HBM ledger: device-memory attribution, budget watchdog, OOM
post-mortem (ISSUE 9).

The metrics registry counts *launches*, the flight recorder replays
*time* — device **bytes** were still dark: an OOM died with a bare
``RESOURCE_EXHAUSTED`` and nothing could say which subsystem owned the
HBM that filled up.  This module is the memory half of the
observability story (the TF whitepaper's per-allocator accounting that
drives placement, arxiv 1605.08695 §3.2; MXNet's planned-allocation
design, arxiv 1512.01274 §4) — attribution as product infrastructure,
not a debugging afterthought:

  * **weakref ledger** — every ``NDArray`` registers itself at
    creation (``register_nd``; raw jax / numpy buffers register via
    ``register``/``register_host``) under the innermost
    ``memory_scope("optimizer_state")`` tag on the current thread.
    Entries are weakrefs with a death callback, so the ledger tracks
    LIVE bytes with zero sweeps and can never pin a buffer.
  * **attribution surfaces** — ``report()`` (per-tag live/peak bytes,
    top-N buffers with shape/dtype/tag, the untagged remainder called
    out explicitly), ``snapshot()["memory"]`` gauges with bounded tag
    labels, and per-phase net-delta records in the flight ring
    (``flight.phase_span(..., mem=True)``) so a Perfetto timeline
    shows *which phase grew HBM*.
  * **budget watchdog** — ``MXNET_HBM_BUDGET_MB`` arms a soft budget
    over tracked device bytes: one warning at 90%, a typed
    ``HBMBudgetError`` past 100% — fail *before* the hardware does,
    with attribution attached.
  * **OOM post-mortem** — ``oom_guard(site)`` wraps the dispatch
    chokepoints (executor, fused update, serving dispatch); a caught
    ``RESOURCE_EXHAUSTED`` auto-dumps ledger report + flight ring to
    ``MXNET_FLIGHT_DIR`` (rate-limited, off-thread per the flight
    handler rules) and re-raises a typed ``DeviceMemoryError``.  The
    ``memory.oom`` faultinject site makes the whole path chaos-testable
    without real HBM pressure.

Overhead contract (the ``MXNET_METRICS_ENABLED`` discipline):
``MXNET_MEMORY_LEDGER=0`` reduces every hook to ONE module-global
boolean test — no weakref, no dict write, no tag lookup.  Enabled, a
registration costs one weakref + one counter update; the bench
``memory`` rider pins fused-trainer overhead at ≤2% steps/s.

Accuracy notes: live bytes are computed from shape/dtype metadata
(never a device sync); wrappers sharing one device buffer (views,
``detach()``) are deduplicated by buffer identity in ``report()``,
while the cheap per-tag counters count each registration — the
counters drive the budget check and the phase deltas, the report is
the audit.
"""
from __future__ import annotations

import collections
import contextlib
import json
import logging
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

from ..base import MXNetError, getenv, atomic_write, unique_path
from ..analysis import sanitizer as _san

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "enabled", "enable", "disable", "memory_scope",
           "current_tag", "register", "register_nd", "register_host",
           "tracked_bytes", "live_by_tag", "report", "snapshot_summary",
           "refresh_gauge", "nbytes_of",
           "reset", "configure", "note_compiled", "compiled_stats",
           "compiled_stats_dict", "oom_guard", "is_oom",
           "wait_oom_dump", "last_oom", "DeviceMemoryError",
           "HBMBudgetError", "UNTAGGED", "budget_bytes",
           "headroom_bytes", "set_budget_arbiter", "ensure_headroom"]

# -- the fast-path switch ----------------------------------------------------
# Hooks across ndarray/gluon/serving/checkpoint read this module global
# directly: `if memory.ENABLED: memory.register_nd(self)`.
ENABLED: bool = getenv("MXNET_MEMORY_LEDGER", True)
#: soft HBM budget in MB over TRACKED device bytes (0 = watchdog off):
#: one warning when tracked bytes cross 90% of it, a typed
#: HBMBudgetError past 100% — before the hardware raises
BUDGET_MB: float = float(getenv("MXNET_HBM_BUDGET_MB", 0.0))
#: minimum seconds between OOM post-mortem dumps (tests set 0)
OOM_DUMP_MIN_S: float = 30.0

#: the tag live/peak counters file untagged registrations under — kept
#: out of user tag space (scopes reject it)
UNTAGGED = "_untagged"


class DeviceMemoryError(MXNetError):
    """Typed re-raise of a device RESOURCE_EXHAUSTED caught at a
    dispatch chokepoint — by the time this propagates, the post-mortem
    (ledger report + flight ring) is being written to
    ``MXNET_FLIGHT_DIR``."""


class HBMBudgetError(MXNetError):
    """Tracked device bytes exceeded ``MXNET_HBM_BUDGET_MB`` — the
    soft-budget watchdog failing BEFORE the hardware does.  The message
    carries the per-tag attribution at the moment of the crossing."""


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


# -- tag scopes ---------------------------------------------------------------
_tls = threading.local()


def current_tag() -> Optional[str]:
    """Innermost ``memory_scope`` tag on this thread (None outside)."""
    stack = getattr(_tls, "tags", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def memory_scope(tag: str):
    """Attribute every buffer registered on this thread inside the
    block to ``tag`` (nestable — the innermost scope wins).  Tags must
    come from a bounded literal set: each distinct tag is a forever
    entry in the per-tag counters and a label value on the
    ``mxnet_memory_ledger_bytes`` gauge."""
    if not ENABLED:
        # MXNET_MEMORY_LEDGER=0 contract: hot-path callers wrap every
        # batch/step in a scope — skip tag validation and the TLS
        # stack entirely, nothing downstream will read the tag anyway
        yield
        return
    if not isinstance(tag, str) or not tag or tag.startswith("_"):
        raise MXNetError(f"memory_scope tag must be a non-empty str not "
                         f"starting with '_', got {tag!r}")
    stack = getattr(_tls, "tags", None)
    if stack is None:
        stack = _tls.tags = []
    stack.append(tag)
    try:
        yield
    finally:
        stack.pop()


# -- the ledger ---------------------------------------------------------------
# entry: token -> (ref, tag, nbytes, space)
# space: "device" (jax buffers / NDArrays) or "host" (checkpoint
# snapshot twins).  The death callback queues a record; the next
# ledger operation drains the queue under the lock — no sweep ever
# runs and no counter update happens in gc context (see _dead below).
# RLock on purpose: a weakref death callback can fire from the garbage
# collector at ANY allocation point — including inside a ledger
# critical section on the same thread (a dead reference cycle holding
# a registered NDArray); the append-only callback needs no lock, but
# keeping the ledger lock reentrant means even a future callback that
# does take it cannot self-deadlock.
_lock = _san.make_rlock("memory.ledger")
_entries: Dict[int, tuple] = {}
_by_id: Dict[int, int] = {}         # id(live tracked obj) -> token
_next_token = 0
_live: Dict[tuple, float] = {}      # (space, tag) -> live bytes
_peak: Dict[tuple, float] = {}      # (space, tag) -> peak live bytes
_counts: Dict[tuple, int] = {}      # (space, tag) -> live buffer count
_device_total = 0.0                 # running sum over device-space tags
_budget_warned = False


def nbytes_of(obj) -> int:
    """Byte size from metadata only — never a device sync.  Computed
    as itemsize × prod(shape) rather than ``.nbytes``: the jax
    ``ArrayImpl.nbytes`` property costs ~7µs of python-side shape
    plumbing per call, ~10× this whole registration's budget."""
    try:
        n = obj.dtype.itemsize
        for d in obj.shape:
            n *= d
        return n
    except (AttributeError, TypeError):
        pass
    n = getattr(obj, "nbytes", None)
    if n is not None:
        try:
            return int(n)
        except (TypeError, ValueError):
            pass
    return 0


# Death callbacks only APPEND here (deque appends are GIL-atomic, no
# lock, no read-modify-write): a callback fires from the garbage
# collector at ANY allocation point — including in the middle of a
# counter update on the same thread, where a direct decrement would be
# overwritten by the interrupted frame's stale value (lost-decrement
# drift).  The queue is drained inside the lock by the next ledger
# operation; a nested callback during a drain just appends again.
_dead = collections.deque()


def _on_death(token: int, space_tag: tuple, nbytes: int) -> None:
    _dead.append((token, space_tag, nbytes))


def _drain_dead_locked() -> None:
    """Apply queued death records to the counters.  Caller holds
    ``_lock``; entries dropped by ``reset()`` are skipped (a buffer
    registered before a reset dying after it must not corrupt the
    fresh counters)."""
    global _device_total
    while _dead:
        try:
            token, st, nb = _dead.popleft()
        except IndexError:
            break
        e = _entries.pop(token, None)
        if e is None:
            continue
        if _by_id.get(e[4]) == token:
            del _by_id[e[4]]
        _live[st] = max(0.0, _live.get(st, 0.0) - nb)
        _counts[st] = max(0, _counts.get(st, 0) - 1)
        if st[0] == "device":
            _device_total = max(0.0, _device_total - nb)


def register(obj, tag: Optional[str] = None, space: str = "device",
             nbytes: Optional[int] = None):
    """Track ``obj`` (any weakref-able array-ish: jax.Array, numpy,
    NDArray) under ``tag`` (default: the current ``memory_scope``; no
    scope → the untagged remainder).  Returns ``obj`` so call sites can
    wrap in-line.  One boolean test when the ledger is off.

    Hot-path discipline: this runs for EVERY NDArray creation, so the
    entry stores only (ref, tag, bytes, space) — shape/dtype are read
    from the live object at ``report()`` time, never eagerly."""
    global _next_token, _device_total, _budget_warned
    if not ENABLED:
        return obj
    if tag is None:
        tag = current_tag() or UNTAGGED
    nb = nbytes_of(obj) if nbytes is None else nbytes
    st = (space, tag)
    budget_exceeded = None
    oid = id(obj)
    with _lock:
        if _dead:
            _drain_dead_locked()
        prev_tok = _by_id.get(oid)
        if prev_tok is not None:
            prev = _entries.get(prev_tok)
            if prev is not None and prev[0]() is obj:
                # re-registration of a still-live object (executor
                # re-preparing the same committed mesh arrays each
                # step, a load-path parameter retagged from _untagged
                # to param): MOVE the bytes to the new (space, tag)
                # instead of double counting.  Drop the old entry so
                # the old weakref's death callback becomes a no-op —
                # the fresh entry below carries the new accounting.
                _, p_tag, p_nb, p_space, _o = prev
                del _entries[prev_tok]
                p_st = (p_space, p_tag)
                _live[p_st] = max(0.0, _live.get(p_st, 0.0) - p_nb)
                _counts[p_st] = max(0, _counts.get(p_st, 0) - 1)
                if p_space == "device":
                    _device_total = max(0.0, _device_total - p_nb)
            # else: a dead buffer's id was reused — fall through and
            # let the fresh entry below take over the mapping
        token = _next_token = _next_token + 1
        try:
            ref = weakref.ref(obj, lambda _r, t=token, s=st, n=nb:
                              _on_death(t, s, n))
        except TypeError:
            return obj  # not weakref-able: out of ledger scope
        _entries[token] = (ref, tag, nb, space, oid)
        _by_id[oid] = token
        live = _live[st] = _live.get(st, 0.0) + nb
        _counts[st] = _counts.get(st, 0) + 1
        if live > _peak.get(st, 0.0):
            _peak[st] = live
        if space == "device":
            _device_total += nb
            budget = BUDGET_MB * 1048576.0
            if budget > 0.0:
                if _device_total > budget:
                    budget_exceeded = _device_total
                    # snapshot while still under the lock: the raise
                    # below must never trip over a concurrent register
                    live_items = list(_live.items())
                elif _device_total > 0.9 * budget and not _budget_warned:
                    _budget_warned = True
                    log.warning(
                        "HBM budget watchdog: tracked device bytes %.1f MB "
                        "crossed 90%% of MXNET_HBM_BUDGET_MB=%.0f",
                        _device_total / 1048576, BUDGET_MB)
                elif _device_total < 0.8 * budget:
                    _budget_warned = False
    if budget_exceeded is not None:
        # the entry stays registered (accounting is consistent; the
        # buffer exists whether or not the caller survives this raise)
        attribution = {t: round(v / 1048576, 2)
                       for (sp, t), v in sorted(live_items)
                       if sp == "device" and v}
        raise HBMBudgetError(
            f"tracked device bytes {budget_exceeded / 1048576:.1f} MB "
            f"exceed MXNET_HBM_BUDGET_MB={BUDGET_MB:.0f} — attribution "
            f"(MB): {attribution}")
    return obj


def register_nd(nd_arr) -> None:
    """The NDArray-creation hook: track the WRAPPER (it survives
    ``_set_data`` buffer swaps, so a parameter keeps its tag across
    functional updates) with bytes read from its current buffer."""
    register(nd_arr, nbytes=nbytes_of(getattr(nd_arr, "_data", None)))


def register_host(obj, tag: Optional[str] = None):
    """Track a host-side buffer (numpy) — the ledger twin for host-RAM
    hogs like async-checkpoint snapshots."""
    return register(obj, tag=tag, space="host")


# -- queries ------------------------------------------------------------------
def tracked_bytes(space: str = "device") -> float:
    """Cheap total of tracked live bytes (O(1) read of the running
    device sum; O(#tags) for host) — the phase-delta sampling hook."""
    if _dead:
        with _lock:
            _drain_dead_locked()
    if space == "device":
        return _device_total
    with _lock:
        return sum(v for (sp, _t), v in _live.items() if sp == space)


def live_by_tag(space: str = "device") -> Dict[str, float]:
    with _lock:
        _drain_dead_locked()
        return {t: v for (sp, t), v in sorted(_live.items())
                if sp == space and v > 0}


def _shard_info(handle, nb_now: int):
    """(per-shard bytes, spec string or None) for a buffer.  A
    GSPMD-sharded jax.Array holds only its shard per device —
    ``Sharding.shard_shape`` gives the slice one device stores; a
    replicated or single-device array returns the logical bytes and no
    spec.  Guarded: the ledger tracks numpy and wrappers too."""
    try:
        sh = getattr(handle, "sharding", None)
        if sh is None or getattr(sh, "num_devices", 1) <= 1:
            return nb_now, None
        sshape = sh.shard_shape(tuple(handle.shape))
        n = 1
        for d in sshape:
            n *= int(d)
        itemsize = getattr(getattr(handle, "dtype", None), "itemsize", 0)
        shard_nb = int(n * itemsize) or nb_now
        spec = getattr(sh, "spec", None)
        return shard_nb, (str(spec) if spec is not None else None)
    except Exception:  # noqa: BLE001 — accounting must never raise
        return nb_now, None


def report(top: int = 10) -> dict:
    """The audit view: per-tag live/peak/count (device and host
    sections), the ``top`` largest live buffers with shape/dtype/tag,
    the untagged remainder called out explicitly, per-program compiled
    stats, and the raw per-device ``memory_stats()`` when the backend
    reports one.  Live bytes here are DEDUPLICATED by underlying buffer
    identity — wrappers sharing a device buffer count once."""
    with _lock:
        _drain_dead_locked()
        entries = list(_entries.values())
        peaks = dict(_peak)
        compiled = {k: dict(v) for k, v in _compiled.items()}
    # dedupe by buffer id; deref outside the lock (callbacks may fire)
    buffers: List[dict] = []
    seen: Dict[int, int] = {}
    agg: Dict[tuple, dict] = {}
    for ref, tag, nb, space, _oid in entries:
        obj = ref()
        if obj is None:
            continue
        handle = getattr(obj, "_data", obj)
        hid = id(handle)
        if hid in seen:
            continue
        seen[hid] = 1
        nb_now = nbytes_of(handle) or nb
        # GSPMD-sharded arrays: `bytes` is the LOGICAL (global) size;
        # shard_bytes is what one device actually holds — the per-tag
        # shard total below is the real per-device HBM cost, not the
        # replicated sum
        shard_nb, spec = _shard_info(handle, nb_now)
        st = (space, tag)
        a = agg.setdefault(st, {"live_bytes": 0, "buffers": 0,
                                "shard_bytes": 0})
        a["live_bytes"] += nb_now
        a["shard_bytes"] += shard_nb
        a["buffers"] += 1
        entry = {"tag": tag, "space": space, "bytes": nb_now,
                 "shape": tuple(getattr(handle, "shape", ()) or ()),
                 "dtype": str(getattr(handle, "dtype", "?"))}
        if spec is not None:
            entry["shard_bytes"] = shard_nb
            entry["spec"] = spec
        buffers.append(entry)
    buffers.sort(key=lambda b: -b["bytes"])

    def _section(space: str) -> dict:
        tags = {t: {"live_bytes": int(v["live_bytes"]),
                    "buffers": v["buffers"],
                    "peak_bytes": int(peaks.get((space, t), 0.0)),
                    **({"shard_bytes": int(v["shard_bytes"])}
                       if v["shard_bytes"] != v["live_bytes"] else {})}
                for (sp, t), v in sorted(agg.items()) if sp == space}
        untagged = tags.pop(UNTAGGED, {"live_bytes": 0, "buffers": 0,
                                       "peak_bytes": 0})
        tagged = sum(v["live_bytes"] for v in tags.values())
        total = tagged + untagged["live_bytes"]
        return {"tags": tags, "tagged_bytes": int(tagged),
                "untagged": untagged,
                "untagged_bytes": int(untagged["live_bytes"]),
                "total_bytes": int(total),
                "attribution_pct": round(100.0 * tagged / total, 2)
                if total else 100.0}

    from .metrics import hbm_stats
    try:
        from ..parallel.mesh import current_mesh, mesh_signature
        mesh_sig = mesh_signature(current_mesh())
    except Exception:  # noqa: BLE001
        mesh_sig = "replicated"
    return {"enabled": ENABLED,
            "device": _section("device"),
            "host": _section("host"),
            "top": buffers[:max(0, top)],
            "compiled": compiled,
            "budget_mb": BUDGET_MB,
            "mesh": mesh_sig,
            "hbm": hbm_stats()}


def _live_split() -> tuple:
    """Drain dead buffers under the lock, then split live bytes into
    per-space ``{tag: bytes}`` dicts (zero-byte tags dropped) — the one
    place the gauge/snapshot filtering rule lives, so the snapshot()-fed
    and render-fed gauge refreshes can't drift apart."""
    with _lock:
        _drain_dead_locked()
        live = dict(_live)
        peaks = dict(_peak)
    dev = {t: int(v) for (sp, t), v in sorted(live.items())
           if sp == "device" and v > 0}
    host = {t: int(v) for (sp, t), v in sorted(live.items())
            if sp == "host" and v > 0}
    return dev, host, peaks


def snapshot_summary() -> dict:
    """The compact block ``observability.snapshot()["memory"]`` carries
    (and the export-time feed of the ``mxnet_memory_ledger_bytes``
    gauge — bounded tag labels, untagged included as ``_untagged``)."""
    dev, host, peaks = _live_split()
    tagged = sum(v for t, v in dev.items() if t != UNTAGGED)
    untagged = dev.get(UNTAGGED, 0)
    total = tagged + untagged
    out = {"enabled": ENABLED,
           "tracked_bytes": int(total),
           "tags": dev,
           "host_tags": host,
           "untagged_bytes": int(untagged),
           "attribution_pct": round(100.0 * tagged / total, 2)
           if total else 100.0,
           "peak_by_tag": {t: int(v) for (sp, t), v in sorted(peaks.items())
                           if sp == "device" and v > 0},
           "budget_mb": BUDGET_MB,
           "oom": dict(_last_oom)}
    _refresh_gauge_from(dev, host)
    return out


def _refresh_gauge_from(dev: Dict[str, int], host: Dict[str, int]) -> None:
    try:
        from . import metrics as _metrics
        if _metrics.ENABLED:
            # export-time gauge refresh (the on-demand-expensive rule):
            # one atomic child-set swap, so dead tags don't linger AND
            # a concurrent scrape never renders a half-rebuilt gauge
            _metrics.MEMORY_LEDGER_BYTES.replace_children(
                [({"tag": t, "space": "device"}, v)
                 for t, v in dev.items()] +
                [({"tag": t, "space": "host"}, v)
                 for t, v in host.items()])
    except Exception:  # noqa: BLE001 — export must never fail on gauges
        pass


def refresh_gauge() -> None:
    """Push current per-tag live bytes onto ``mxnet_memory_ledger_bytes``.
    Called at every export chokepoint — ``snapshot()`` and the
    Prometheus/JSON render paths — so a scrape that never goes through
    ``snapshot()`` still sees fresh values; never on the hot path."""
    dev, host, _ = _live_split()
    _refresh_gauge_from(dev, host)


# -- budget arbitration -------------------------------------------------------
# The soft budget above only WATCHES (warn at 90%, raise past 100%);
# arbitration is the layer that NEGOTIATES: before a large allocation,
# a subsystem asks ensure_headroom() whether the bytes fit, and a
# registered arbiter — the serving ModelRegistry's LRU evictor — gets
# the chance to free colder memory first.  The k+1'th model becomes a
# policy decision instead of an OOM (docs/multi_model.md).
_arbiter = None  # (deficit_bytes: float, why: str) -> freed estimate


def budget_bytes() -> float:
    """The armed soft budget in bytes (0.0 = budget off)."""
    return BUDGET_MB * 1048576.0


def headroom_bytes(budget: Optional[float] = None) -> float:
    """Budget minus tracked live device bytes (+inf when no budget is
    armed and no override is given).  ``budget`` overrides the env-armed
    ``MXNET_HBM_BUDGET_MB`` in bytes — a registry running its own budget
    passes it here so one arbitration code path serves both."""
    b = budget_bytes() if budget is None else float(budget)
    if b <= 0.0:
        return float("inf")
    return b - tracked_bytes()


def set_budget_arbiter(fn):
    """Install ``fn(deficit_bytes, why) -> freed_bytes_estimate`` as the
    process arbiter (None uninstalls).  Returns the previous arbiter.
    The arbiter is called OUTSIDE the ledger lock and must be safe to
    invoke from any thread that allocates."""
    global _arbiter
    prev, _arbiter = _arbiter, fn
    return prev


def ensure_headroom(nbytes: float, why: str = "",
                    budget: Optional[float] = None) -> bool:
    """The budget arbitration chokepoint: would ``nbytes`` more tracked
    device bytes still fit?  On a shortfall the registered arbiter is
    asked to free the deficit (LRU eviction), then the answer is
    re-evaluated.  True when the allocation fits (always, with no budget
    armed); False means the caller should degrade (typed
    ``ModelUnavailable`` / defer) instead of allocating into a certain
    ``HBMBudgetError``."""
    h = headroom_bytes(budget)
    if h >= nbytes:
        return True
    fn = _arbiter
    if fn is not None:
        try:
            fn(float(nbytes) - h, why)
        except Exception as e:  # noqa: BLE001 — arbiter is best-effort
            log.warning("budget arbiter failed (%s): %s", why, str(e))
        return headroom_bytes(budget) >= nbytes
    return False


# -- compiled-program stats (CompiledMemoryStats registry) --------------------
_compiled: Dict[str, dict] = {}


def compiled_stats_dict(stats) -> dict:
    """Uniform structured view of a jax ``CompiledMemoryStats`` across
    jax versions: always the same keys, with ``peak_bytes`` estimated
    as the live-buffer sum (and flagged ``peak_estimated``) on jax
    builds whose stats lack ``peak_memory_in_bytes`` (< 0.5).  Returns
    ``{}`` when the backend reports no stats (older PJRT) — callers
    treat falsy as unavailable."""
    if stats is None:
        return {}
    out = {
        "temp_bytes": int(stats.temp_size_in_bytes),
        "argument_bytes": int(stats.argument_size_in_bytes),
        "output_bytes": int(stats.output_size_in_bytes),
        "alias_bytes": int(stats.alias_size_in_bytes),
        "generated_code_bytes": int(stats.generated_code_size_in_bytes),
    }
    peak = getattr(stats, "peak_memory_in_bytes", None)
    if peak is None:
        out["peak_bytes"] = (out["temp_bytes"] + out["argument_bytes"]
                             + out["output_bytes"] + out["alias_bytes"])
        out["peak_estimated"] = True
    else:
        out["peak_bytes"] = int(peak)
        out["peak_estimated"] = False
    return out


def note_compiled(name: str, stats: dict) -> None:
    """File one program's compiled memory stats under ``name`` (bounded
    names: ``executor``, ``serve_bucket:<label>`` over the bucket
    lattice).  Shows up in ``report()["compiled"]``."""
    if not ENABLED or not stats:
        return
    with _lock:
        _compiled[name] = dict(stats)


def compiled_stats() -> Dict[str, dict]:
    with _lock:
        return {k: dict(v) for k, v in _compiled.items()}


# -- OOM post-mortem ----------------------------------------------------------
_last_oom: dict = {}
# None, not 0.0: time.monotonic() can be < OOM_DUMP_MIN_S early after
# boot, and the FIRST post-mortem must never look rate-limited
_last_oom_dump: Optional[float] = None
# starts SET: "no dump in flight" — wait_oom_dump() on a process that
# never OOM'd must return immediately, not stall out its timeout
_oom_dump_done = threading.Event()
_oom_dump_done.set()
_oom_dumps = 0


def is_oom(exc: BaseException) -> bool:
    """Does ``exc`` look like device memory exhaustion?  Matches the
    real thing (jaxlib ``XlaRuntimeError`` carrying RESOURCE_EXHAUSTED)
    and the synthetic ``memory.oom`` faultinject site (its message
    names the site), never generic errors."""
    s = f"{type(exc).__name__}: {exc}"
    return "RESOURCE_EXHAUSTED" in s or "memory.oom" in s


@contextlib.contextmanager
def oom_guard(site: str):
    """Wrap a dispatch chokepoint: a caught RESOURCE_EXHAUSTED triggers
    the rate-limited off-thread post-mortem (ledger report + flight
    ring to ``MXNET_FLIGHT_DIR``, atomic writes) and re-raises typed.
    One boolean test when the ledger is off."""
    if not ENABLED:
        yield
        return
    try:
        yield
    except DeviceMemoryError:
        raise  # an inner guard already handled it — never dump twice
    except Exception as e:  # noqa: BLE001 — filtered to OOM below
        if not is_oom(e):
            raise
        _post_mortem(site, e)
        raise DeviceMemoryError(
            f"device memory exhausted at {site} — post-mortem (ledger "
            f"report + flight ring) dumping to "
            f"{os.environ.get('MXNET_FLIGHT_DIR', '.') or '.'}; "
            f"original: {type(e).__name__}: {e}") from e


def _post_mortem(site: str, exc: BaseException) -> None:
    global _last_oom_dump
    now = time.monotonic()
    with _lock:
        rate_limited = _last_oom_dump is not None and \
            now - _last_oom_dump < OOM_DUMP_MIN_S
        if not rate_limited:
            _last_oom_dump = now
    rec = {"site": site, "error": f"{type(exc).__name__}: {exc}",
           "rate_limited": rate_limited}
    if rate_limited:
        # no new dump this window — keep pointing consumers
        # (wait_oom_dump, snapshot()["memory"]["oom"], readyz) at the
        # on-disk post-mortem that opened the rate window
        for k in ("report_path", "flight_path"):
            if k in _last_oom:
                rec[k] = _last_oom[k]
    _last_oom.clear()
    _last_oom.update(rec)
    if rate_limited:
        return
    _oom_dump_done.clear()
    # off-thread per the flight handler rules: the failing thread may
    # hold subsystem locks the dump path would need; the ledger/ring
    # already hold the moments before the OOM regardless of scheduling.
    # The dump thread gets its OWN copy of the record — a second OOM
    # rewriting _last_oom mid-dump must not change what gets written
    # (or which record the report_path lands on)
    threading.Thread(target=_bg_oom_dump, args=(site, rec),
                     name="mxt-oom-dump", daemon=True).start()


def _bg_oom_dump(site: str, rec: dict) -> None:
    global _oom_dumps
    try:
        from . import journal as _journal
        if _journal.ENABLED:
            # cross-reference the run journal in the OOM report (and
            # vice versa below) — pivot from badput row to timeline
            rec["run_id"] = _journal.run_id()
            rec["journal_path"] = _journal.path()
        d = os.environ.get("MXNET_FLIGHT_DIR", ".") or "."
        os.makedirs(d, exist_ok=True)
        path = unique_path(d, "oom", ".json")
        atomic_write(path, json.dumps(
            {"oom": dict(rec), "report": report(top=20)},
            default=str))
        rec["report_path"] = path
        from . import flight as _flight
        if _flight.ENABLED:
            rec["flight_path"] = _flight.dump(reason="oom")
        # publish onto last_oom() only if a newer OOM hasn't replaced
        # the record this dump belongs to
        if _last_oom.get("site") == rec["site"] and \
                _last_oom.get("error") == rec["error"]:
            _last_oom.update(rec)
        else:
            # a newer (rate-limited) OOM replaced the record while this
            # dump was in flight — it belongs to the same rate window,
            # so consumers still get pointed at the on-disk post-mortem
            for k in ("report_path", "flight_path"):
                if k in rec:
                    _last_oom.setdefault(k, rec[k])
        _oom_dumps += 1
        log.error("HBM OOM post-mortem at %s: %s", site, path)
        if _journal.ENABLED:
            _journal.emit("oom", durable=True, site=site,
                          report_path=rec.get("report_path"),
                          flight_path=rec.get("flight_path"))
    except Exception as e:  # noqa: BLE001 — a failed dump must not mask
        log.warning("OOM post-mortem dump failed: %s", e)
    finally:
        _oom_dump_done.set()


def wait_oom_dump(timeout: float = 10.0) -> Optional[str]:
    """Test/ops hook: block until the in-flight OOM dump (if any)
    finishes; returns the report path (None when nothing dumped)."""
    _oom_dump_done.wait(timeout)
    return _last_oom.get("report_path")


def last_oom() -> dict:
    return dict(_last_oom)


def oom_dumps() -> int:
    return _oom_dumps


# -- lifecycle ----------------------------------------------------------------
def reset() -> None:
    """Drop every entry/counter and the OOM/budget state (tests).
    Weakref callbacks from still-live buffers registered before the
    reset become no-ops (their tokens are gone)."""
    global _device_total, _budget_warned, _last_oom_dump, _oom_dumps
    global _arbiter
    _arbiter = None  # a dead registry's evictor must not outlive it
    with _lock:
        _dead.clear()
        _entries.clear()
        _by_id.clear()
        _live.clear()
        _peak.clear()
        _counts.clear()
        _compiled.clear()
        _device_total = 0.0
        _budget_warned = False
    _last_oom.clear()
    _last_oom_dump = None
    _oom_dumps = 0
    _oom_dump_done.set()


def configure(budget_mb: Optional[float] = None,
              oom_dump_min_s: Optional[float] = None) -> None:
    """Re-read knobs (tests / long-lived jobs that flip the env)."""
    global BUDGET_MB, OOM_DUMP_MIN_S, _budget_warned
    if budget_mb is not None:
        BUDGET_MB = float(budget_mb)
    else:
        BUDGET_MB = float(getenv("MXNET_HBM_BUDGET_MB", 0.0))
    if oom_dump_min_s is not None:
        OOM_DUMP_MIN_S = float(oom_dump_min_s)
    _budget_warned = False
