"""Structured tracing spans that land in BOTH timelines.

`with trace_span("forward"):` emits
  - a python-side Chrome-trace complete event ("X") into the profiler's
    `_events` buffer (dumped by `profiler.dump_profile()`), and
  - a `jax.profiler.TraceAnnotation` scope, so the same span shows up
    inside the XLA xplane trace next to the device ops it covers
    (TensorBoard / Perfetto line the two up by wall-clock).

`step_span(step)` additionally uses `jax.profiler.StepTraceAnnotation`,
which TensorBoard's profile plugin uses for per-step breakdowns.

Fast path: when the profiler is stopped, a span is ONE predicate test —
no timestamps, no annotation objects, no allocation beyond the generator
frame.  Nesting is expressed the Chrome-trace way: events on the same
pid/tid whose [ts, ts+dur] ranges contain each other render nested.
"""
from __future__ import annotations

import contextlib
import threading
import time

from ..analysis.sanitizer import make_lock as _make_lock

_tls = threading.local()
_tid_lock = _make_lock("tracing.tid")
_tid_map: dict = {}


def _tid() -> int:
    """Small stable per-thread id (Chrome trace tids are more readable
    than 140-bit thread idents)."""
    t = getattr(_tls, "tid", None)
    if t is None:
        with _tid_lock:
            t = _tid_map.setdefault(threading.get_ident(), len(_tid_map))
        _tls.tid = t
    return t


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def _profiler():
    from .. import profiler
    return profiler


def _annotation(name: str):
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def trace_span(name: str, cat: str = "runtime"):
    """Record `name` as a nested span on both timelines while the
    profiler runs; a no-op predicate test otherwise."""
    prof = _profiler()
    if not prof.is_recording():
        yield
        return
    ann = _annotation(name)
    if ann is not None:
        ann.__enter__()
    _tls.depth = _depth() + 1
    start = time.perf_counter() * 1e6
    try:
        yield
    finally:
        end = time.perf_counter() * 1e6
        _tls.depth -= 1
        if ann is not None:
            ann.__exit__(None, None, None)
        prof.record_event(name, start, end, cat=cat, tid=_tid(),
                          args={"depth": _depth()})


@contextlib.contextmanager
def step_span(step_num: int, name: str = "train"):
    """Step-boundary annotation: xplane StepTraceAnnotation (feeds
    TensorBoard's per-step breakdown) + a Chrome-trace span."""
    prof = _profiler()
    if not prof.is_recording():
        yield
        return
    ann = None
    try:
        import jax
        ann = jax.profiler.StepTraceAnnotation(name, step_num=step_num)
        ann.__enter__()
    except Exception:
        ann = None
    start = time.perf_counter() * 1e6
    try:
        yield
    finally:
        end = time.perf_counter() * 1e6
        if ann is not None:
            ann.__exit__(None, None, None)
        prof.record_event(f"{name}_step", start, end, cat="step",
                          tid=_tid(), args={"step": step_num})


def annotate(name: str):
    """Bare xplane annotation (no python-side event) — for spans that
    only matter relative to device ops."""
    ann = _annotation(name)
    return ann if ann is not None else contextlib.nullcontext()
