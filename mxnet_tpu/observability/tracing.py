"""Structured tracing spans that land in BOTH timelines.

`with trace_span("forward"):` emits
  - a python-side Chrome-trace complete event ("X") into the profiler's
    `_events` buffer (dumped by `profiler.dump_profile()`), and
  - a `jax.profiler.TraceAnnotation` scope, so the same span shows up
    inside the XLA xplane trace next to the device ops it covers
    (TensorBoard / Perfetto line the two up by wall-clock).

`step_span(step)` additionally uses `jax.profiler.StepTraceAnnotation`,
which TensorBoard's profile plugin uses for per-step breakdowns — and,
since ISSUE 8, feeds the always-on flight recorder
(`observability.flight`) even while the profiler is paused/stopped:
both timelines stamp the SAME `time.perf_counter()` monotonic clock,
so flight records and profiler `_events` can never disagree on t0/t1
ordering.

Fast path: when the profiler is stopped, a `trace_span` is ONE
predicate test — no timestamps, no annotation objects, no allocation
beyond the generator frame.  Nesting is expressed the Chrome-trace way:
events on the same pid/tid whose [ts, ts+dur] ranges contain each other
render nested.
"""
from __future__ import annotations

import contextlib
import sys
import threading
import time

from ..analysis.sanitizer import make_lock as _make_lock

_tls = threading.local()
_tid_lock = _make_lock("tracing.tid")
_tid_map: dict = {}


def _tid() -> int:
    """Small stable per-thread id (Chrome trace tids are more readable
    than 140-bit thread idents).  Shared with the flight recorder so
    merged dumps line threads up."""
    t = getattr(_tls, "tid", None)
    if t is None:
        with _tid_lock:
            t = _tid_map.setdefault(threading.get_ident(), len(_tid_map))
        _tls.tid = t
    return t


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def _profiler():
    from .. import profiler
    return profiler


def _flight():
    from . import flight
    return flight


def _annotation(name: str):
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


@contextlib.contextmanager
def trace_span(name: str, cat: str = "runtime"):
    """Record `name` as a nested span on both timelines while the
    profiler runs; a no-op predicate test otherwise.

    Exception-safe depth accounting: the increment/decrement pair and
    the event record sit in `finally` blocks ordered so that a raising
    body (or a raising annotation `__exit__`) can neither leak a depth
    level nor lose the event — the profiler `_events` buffer and the
    flight ring must agree on span nesting after an exception unwinds
    through a step."""
    prof = _profiler()
    if not prof.is_recording():
        yield
        return
    ann = _annotation(name)
    start = time.perf_counter() * 1e6
    _tls.depth = _depth() + 1
    entered = False
    try:
        if ann is not None:
            ann.__enter__()
            entered = True
        try:
            yield
        except BaseException:
            # the annotation sees exactly the exception unwinding
            # through the SPAN BODY — never an unrelated outer
            # exception sys.exc_info() would report on a normal
            # completion inside an except handler, and never an
            # __exit__ on an annotation whose __enter__ raised
            if entered:
                entered = False
                ann.__exit__(*sys.exc_info())
            raise
        if entered:
            entered = False
            ann.__exit__(None, None, None)
    finally:
        end = time.perf_counter() * 1e6
        _tls.depth = _depth() - 1
        prof.record_event(name, start, end, cat=cat, tid=_tid(),
                          args={"depth": _depth()})


@contextlib.contextmanager
def step_span(step_num: int, name: str = "train"):
    """Step-boundary annotation: xplane StepTraceAnnotation (feeds
    TensorBoard's per-step breakdown) + a Chrome-trace span + an
    always-on flight-recorder step record.

    The flight record uses the monotonic `perf_counter` clock whether
    or not the profiler is running — in particular while the profiler
    is PAUSED (is_running but not recording), the step still lands in
    the ring with correctly ordered t0/t1, so a later resume cannot
    interleave out-of-order events between the two timelines.  It also
    feeds the slow-step watchdog (`flight.note`)."""
    prof = _profiler()
    rec = prof.is_recording()
    fl = _flight()
    if not rec and not fl.ENABLED:
        yield
        return
    ann = None
    if rec:
        try:
            import jax
            ann = jax.profiler.StepTraceAnnotation(name, step_num=step_num)
            ann.__enter__()
        except Exception:
            ann = None
    # bounded by construction: callers pass literal step-stream names
    # ("train"), so the derived record name is one entry per stream
    rec_name = name + "_step"
    start = time.perf_counter() * 1e6
    try:
        try:
            yield
        except BaseException:
            # only a body exception reaches the annotation (see
            # trace_span): normal completion inside an outer except
            # handler must not report that handler's exception
            if ann is not None:
                a, ann = ann, None
                a.__exit__(*sys.exc_info())
            raise
        if ann is not None:
            a, ann = ann, None
            a.__exit__(None, None, None)
    finally:
        end = time.perf_counter() * 1e6
        if rec:
            prof.record_event(rec_name, start, end, cat="step",
                              tid=_tid(), args={"step": step_num})
        if fl.ENABLED:
            fl.record(rec_name, "step", start, end, step=step_num,
                      watch=True)


def annotate(name: str):
    """Bare xplane annotation (no python-side event) — for spans that
    only matter relative to device ops."""
    ann = _annotation(name)
    return ann if ann is not None else contextlib.nullcontext()
