"""Runtime-wide observability: structured tracing + metrics + dispatch
accounting (the TPU redesign of the reference's engine profiler,
`src/engine/profiler.cc`).

The reference wired per-op exec stats into the engine because a training
stack you cannot see cannot be optimized — the single worst perf bug in
this port (193 `jax.device_put` RPCs per Module.fit step through the TPU
tunnel, round 2) was invisible until dispatches were hand-counted.  This
package makes that visibility a product API:

  - `mxnet_tpu.observability.metrics` — a process-wide registry of
    counters / gauges / histograms (XLA program launches by kind,
    device_put count + transfer bytes, jit cache hits/misses, engine
    wait stalls, kvstore push/pull bytes + allreduce latency, dataloader
    batch-wait time, HBM usage) with Prometheus-text and JSON exporters.
  - `mxnet_tpu.observability.tracing` — `with trace_span("forward"):`
    spans that land BOTH in the python-side Chrome-trace timeline
    (`profiler._events`) and in the XLA xplane trace
    (`jax.profiler.TraceAnnotation`), so host spans line up with device
    ops in TensorBoard/Perfetto.
  - `dispatch_counts()` — the queryable per-kind XLA-launch/transfer
    tally that `tests/test_dispatch_count.py` pins as an invariant.
  - `mxnet_tpu.observability.flight` — the always-on flight recorder:
    `phase_span(...)` ring-records per-phase step/request timelines
    (data-wait/h2d/allreduce/fused-update, queue-wait/pad/dispatch/
    slice with end-to-end trace ids), `flight.dump()` exports a
    Perfetto-loadable Chrome trace merging training + serving +
    profiler `_events`, and a slow-step/slow-request watchdog
    auto-dumps the ring on anomaly and on SIGUSR2
    (`MXNET_FLIGHT=0` disables; see docs/observability.md).
  - `mxnet_tpu.observability.memory` — the HBM ledger: weakref-tracked
    device/host byte attribution by `memory_scope` tag
    (`memory.report()`, `snapshot()["memory"]`), per-phase net-delta
    memory records in the flight ring, an `MXNET_HBM_BUDGET_MB` soft
    budget, and an OOM post-mortem (`oom_guard` catches
    RESOURCE_EXHAUSTED at the dispatch chokepoints, dumps ledger +
    ring, re-raises typed; `MXNET_MEMORY_LEDGER=0` disables; see
    docs/memory.md).
  - `mxnet_tpu.observability.introspect` — program introspection:
    every compile chokepoint notes its program's analytical cost
    (flops, bytes) + CompiledMemoryStats through one
    `note_program()` surface (`snapshot()["programs"]`,
    `introspect.report()`); `jax.named_scope` layer names thread
    through the graph interpreter so `per_layer()` attributes the
    donated whole-step program's flops to named blocks
    (`MXNET_INTROSPECT_HLO=1` captures the HLO it parses); MFU /
    roofline gauges (`mxnet_mfu`, `MXNET_PEAK_FLOPS` override) and a
    persisted perf-regression sentinel (`MXNET_PERF_BASELINE_DIR`)
    compare the warmed step-time EWMA against a per-(model, platform)
    baseline (`MXNET_INTROSPECT=0` disables; see
    docs/introspection.md).

Overhead discipline: every hot-path hook is guarded by the module-level
`metrics.ENABLED` flag (env `MXNET_METRICS_ENABLED`, default on; set 0
to compile the whole layer down to one boolean test per hook — no dict
allocation, no label formatting, no timestamps).
"""
from __future__ import annotations

from . import metrics
from . import tracing
from . import goodput
from . import journal
from . import flight
from . import timeline
from . import memory
from . import introspect
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
                      enabled, enable, disable, dispatch_counts,
                      step_dispatches, snapshot, render_prometheus,
                      render_json, hbm_stats)
from .tracing import trace_span, step_span, annotate
from .flight import phase_span, trace_scope, new_trace_id
from .memory import memory_scope, oom_guard, DeviceMemoryError, HBMBudgetError

__all__ = [
    "metrics", "tracing", "flight", "timeline", "memory", "introspect",
    "goodput", "journal",
    "Counter",
    "Gauge", "Histogram", "MetricsRegistry", "REGISTRY", "enabled",
    "enable", "disable", "dispatch_counts", "step_dispatches", "snapshot",
    "render_prometheus", "render_json", "hbm_stats",
    "trace_span", "step_span", "annotate",
    "phase_span", "trace_scope", "new_trace_id",
    "memory_scope", "oom_guard", "DeviceMemoryError", "HBMBudgetError",
]
