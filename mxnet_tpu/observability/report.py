"""Offline run reporter: render a run summary from the crash-durable
journal (+ flight dumps) — ``python -m mxnet_tpu.observability.report
<run_dir>`` (ISSUE 16).

The journal (``journal.py``) is written to survive the process; this is
the tool that reads it afterwards.  It answers the operator's morning
questions without a live process to scrape: what run is this, how many
times did it (re)start, what fraction of wall-clock was goodput, how
often did the supervisor retry/rewind/stall, what was the checkpoint
cadence, where did MFU trend, and which post-mortem/flight dumps hold
the detail.  ``--diff`` renders two runs side by side (the
before/after-a-fix view); ``--json`` emits the machine-readable summary
for dashboards.

The module itself touches only the standard library — summarizing a
dead run must not require the runtime the run used.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

__all__ = ["load_journal", "summarize_run", "render", "render_diff",
           "find_run_dir", "main"]

#: events the timeline section renders, in severity order
_TIMELINE_EVENTS = ("supervisor_retry", "supervisor_divergence",
                    "supervisor_stall", "post_mortem", "oom",
                    "preempted", "slo_burn", "perf_regression",
                    "serve_degradation")


def find_run_dir(path: str) -> str:
    """Accept a run dir (holds ``journal*.jsonl``) or a parent of run
    dirs (newest journal wins) — ``make report`` points at the parent."""
    if glob.glob(os.path.join(path, "journal*.jsonl")):
        return path
    candidates = glob.glob(os.path.join(path, "*", "journal.jsonl"))
    if not candidates:
        raise FileNotFoundError(
            f"no journal.jsonl under {path!r} (is MXNET_RUN_DIR set for "
            "the runs you want reported?)")
    return os.path.dirname(max(candidates, key=os.path.getmtime))


def load_journal(run_dir: str) -> List[dict]:
    """Every parseable journal entry, rotation-aware (``journal.1`` is
    the older generation), in write order.  Torn tails — the SIGKILL
    case the journal exists for — are skipped, not fatal."""
    entries: List[dict] = []
    for fname in ("journal.1.jsonl", "journal.jsonl"):
        fpath = os.path.join(run_dir, fname)
        if not os.path.exists(fpath):
            continue
        with open(fpath, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    e = json.loads(raw)
                except ValueError:
                    continue  # torn line (crash mid-write)
                if isinstance(e, dict) and "event" in e:
                    entries.append(e)
    return entries


def _last_goodput(entries: List[dict]) -> Optional[dict]:
    """The most recent goodput view in the journal (milestones embed
    ``goodput_pct`` + per-class seconds)."""
    for e in reversed(entries):
        if e.get("classes") is not None:
            return {"goodput_pct": e.get("goodput_pct"),
                    "classes": e.get("classes")}
    return None


def summarize_run(run_dir: str) -> dict:
    """The machine-readable run summary the renderers (and tests)
    consume."""
    entries = load_journal(run_dir)
    if not entries:
        raise FileNotFoundError(f"journal under {run_dir!r} is empty")
    starts = [e for e in entries if e["event"] == "process_start"]
    times = [e["t"] for e in entries if isinstance(e.get("t"), (int, float))]
    counts: Dict[str, int] = {}
    for e in entries:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    # downtime between incarnations: last entry of one process to the
    # process_start of the next — reported beside the taxonomy (the
    # dead process could not meter its own absence)
    downtime = 0.0
    for s in starts[1:]:
        prior = [t for t in times if t < s["t"]]
        if prior:
            downtime += max(0.0, s["t"] - max(prior))
    milestones = [e for e in entries if e["event"] == "milestone"]
    saves = [e for e in entries if e["event"] == "checkpoint_save"]
    save_steps = [e.get("step") for e in saves if e.get("step") is not None]
    cadence = None
    if len(save_steps) >= 2:
        cadence = (save_steps[-1] - save_steps[0]) / (len(save_steps) - 1)
    timeline = [
        {"t": e.get("t"), "event": e["event"], "step": e.get("step"),
         "detail": {k: v for k, v in e.items()
                    if k not in ("t", "event", "run", "pid", "step")}}
        for e in entries if e["event"] in _TIMELINE_EVENTS]
    mfu = [{"step": e.get("step"), "mfu": e.get("mfu")}
           for e in milestones if e.get("mfu") is not None]
    dumps = [e.get("dump_path") for e in entries
             if e["event"] == "flight_dump"]
    return {
        "run_dir": os.path.abspath(run_dir),
        "run_id": starts[0].get("run") if starts else
                  entries[0].get("run"),
        "incarnations": len(starts),
        "resumes": counts.get("run_resumed", 0),
        "wall_s": (max(times) - min(times)) if len(times) > 1 else 0.0,
        "downtime_s": downtime,
        "entries": len(entries),
        "event_counts": counts,
        "goodput": _last_goodput(entries),
        "last_step": max((e.get("step") for e in entries
                          if e.get("step") is not None), default=None),
        "checkpoint": {"saves": len(saves), "steps": save_steps,
                       "cadence_steps": cadence},
        "timeline": timeline,
        "mfu_trajectory": mfu,
        "flight_dumps": dumps,
    }


def _fmt_s(x: Optional[float]) -> str:
    return "-" if x is None else f"{x:.1f}s"


def render(s: dict) -> str:
    """Human-readable run summary."""
    lines = [
        f"run {s['run_id']}  ({s['run_dir']})",
        f"  incarnations: {s['incarnations']}  resumes: {s['resumes']}  "
        f"wall: {_fmt_s(s['wall_s'])}  restart downtime: "
        f"{_fmt_s(s['downtime_s'])}",
        f"  journal entries: {s['entries']}  last step: {s['last_step']}",
    ]
    g = s.get("goodput")
    if g and g.get("classes"):
        lines.append(f"  goodput: {g.get('goodput_pct', 0.0):.1f}%")
        for cls, b in sorted(g["classes"].items(),
                             key=lambda kv: -kv[1].get("seconds", 0.0)):
            lines.append(f"    {cls:<18} {b.get('seconds', 0.0):8.2f}s  "
                         f"({b.get('events', 0)} events)")
    else:
        lines.append("  goodput: (no milestone carried a ledger — "
                     "MXNET_GOODPUT off or run too short)")
    ck = s["checkpoint"]
    lines.append(f"  checkpoints: {ck['saves']} saves"
                 + (f", cadence ~{ck['cadence_steps']:.0f} steps"
                    if ck["cadence_steps"] else "")
                 + (f", steps {ck['steps']}" if ck["steps"] else ""))
    if s["mfu_trajectory"]:
        pts = "  ".join(f"{p['step']}:{p['mfu']:.3f}"
                        for p in s["mfu_trajectory"][-8:])
        lines.append(f"  mfu trajectory (step:mfu): {pts}")
    if s["timeline"]:
        lines.append(f"  incidents ({len(s['timeline'])}):")
        for e in s["timeline"][-20:]:
            d = ", ".join(f"{k}={v}" for k, v in e["detail"].items()
                          if v is not None)
            lines.append(f"    [{e['event']}] step={e['step']}"
                         + (f"  {d}" if d else ""))
    else:
        lines.append("  incidents: none")
    if s["flight_dumps"]:
        lines.append(f"  flight dumps: {len(s['flight_dumps'])} "
                     f"(latest: {s['flight_dumps'][-1]})")
    return "\n".join(lines)


def render_diff(a: dict, b: dict) -> str:
    """Two runs side by side: the before/after-a-fix comparison."""
    def _g(s, key, default=0.0):
        g = s.get("goodput") or {}
        return g.get(key) or default

    rows = [("run", a["run_id"], b["run_id"]),
            ("incarnations", a["incarnations"], b["incarnations"]),
            ("wall_s", f"{a['wall_s']:.1f}", f"{b['wall_s']:.1f}"),
            ("goodput_pct", f"{_g(a, 'goodput_pct'):.1f}",
             f"{_g(b, 'goodput_pct'):.1f}"),
            ("last_step", a["last_step"], b["last_step"]),
            ("checkpoint saves", a["checkpoint"]["saves"],
             b["checkpoint"]["saves"]),
            ("incidents", len(a["timeline"]), len(b["timeline"]))]
    classes = sorted(set((a.get("goodput") or {}).get("classes") or {})
                     | set((b.get("goodput") or {}).get("classes") or {}))
    for cls in classes:
        ca = ((a.get("goodput") or {}).get("classes") or {}).get(cls, {})
        cb = ((b.get("goodput") or {}).get("classes") or {}).get(cls, {})
        rows.append((f"  {cls}_s", f"{ca.get('seconds', 0.0):.2f}",
                     f"{cb.get('seconds', 0.0):.2f}"))
    w = max(len(str(r[0])) for r in rows)
    out = [f"{'':<{w}}  {'run A':>24}  {'run B':>24}"]
    out += [f"{str(k):<{w}}  {str(va):>24}  {str(vb):>24}"
            for k, va, vb in rows]
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.observability.report",
        description="Render a run summary from a crash-durable run "
                    "journal (MXNET_RUN_DIR); see docs/goodput.md")
    ap.add_argument("run_dir", help="run dir with journal.jsonl, or a "
                                    "parent dir (newest run wins)")
    ap.add_argument("--diff", metavar="RUN_DIR2",
                    help="second run dir: render both side by side")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the machine-readable summary")
    args = ap.parse_args(argv)
    try:
        a = summarize_run(find_run_dir(args.run_dir))
        if args.diff:
            b = summarize_run(find_run_dir(args.diff))
            if args.as_json:
                print(json.dumps({"a": a, "b": b}, indent=2, default=str))
            else:
                print(render_diff(a, b))
        elif args.as_json:
            print(json.dumps(a, indent=2, default=str))
        else:
            print(render(a))
    except FileNotFoundError as e:
        print(f"report: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
