"""Flight recorder: always-on, low-overhead phase timelines (ISSUE 8).

The metrics registry answers "how many / how much"; the profiler answers
"everything, while someone watches".  Neither answers the production
question "why was step 4182 (or request 9f3-77) slow, twenty minutes
ago?" — by the time anyone attaches a profiler the anomaly is gone.
This module is the black-box recorder in between (the MXNet engine's
per-op timeline dumps, arxiv 1512.01274 §5, rebuilt for the TPU runtime;
TensorFlow's production stall-attribution leans on the same timeline
shape, arxiv 1605.08695):

  * **ring buffers of phase records** — ``phase_span("allreduce", ...)``
    appends ``(name, cat, t0, t1, step, trace_id, labels)`` to a
    fixed-size per-thread ring (``MXNET_FLIGHT_RING`` records/thread).
    Writes are lock-free after the first record on a thread: each
    thread owns its segment, so concurrent producers never contend
    (the one lock guards segment *registration*, once per thread).
    Old records are overwritten (counted as ``drops``) — memory is
    bounded forever.
  * **trace ids** — a per-request id minted at submit and carried
    through queue-wait → admission → pad → dispatch → slice via
    ``trace_scope`` (thread-local), so one request's spans are joinable
    across the batcher/scheduler threads in a dump.
  * **anomaly watchdog** — phases recorded with ``watch=True`` feed a
    per-phase EWMA; a sample exceeding ``MXNET_FLIGHT_SLOW_FACTOR`` ×
    the EWMA triggers an automatic ring dump to ``MXNET_FLIGHT_DIR``
    (rate-limited), capturing the moments *before* the anomaly.
    ``SIGUSR2`` dumps on demand.
  * **exporters** — ``dump()`` writes Chrome trace-event JSON
    (Perfetto-loadable; merges the profiler's ``_events`` so training,
    serving and profiler spans share one timeline), ``summary()``
    returns per-phase p50/p99/total + slowest-N records (surfaced in
    ``observability.snapshot()["flight"]``).

Overhead contract (the ``MXNET_METRICS_ENABLED`` discipline):
``MXNET_FLIGHT=0`` reduces every hook to ONE module-global boolean
test — no timestamps, no tuple, no ring write.  Enabled, a span costs
two ``perf_counter`` reads and one list-slot store; the bench ``flight``
rider pins the fused-trainer overhead at ≤2% steps/s.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..base import getenv, unique_path, atomic_write
from ..analysis import sanitizer as _san
from . import goodput as _goodput
from . import journal as _journal

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "enable", "disable", "enabled", "phase_span",
           "watch_ewma",
           "record", "note", "now_us", "new_trace_id", "trace_scope",
           "current_trace_id", "join_ids", "records", "stats", "dump",
           "summary", "snapshot_summary", "reset", "configure"]

# -- the fast-path switch ----------------------------------------------------
# Hooks across trainer/module/serving/checkpoint/io read this module
# global directly:  `if flight.ENABLED: ...` / phase_span's first test.
ENABLED: bool = getenv("MXNET_FLIGHT", True)
#: per-thread ring capacity, in records
RING: int = int(getenv("MXNET_FLIGHT_RING", 4096))
#: watchdog trigger: sample > SLOW_FACTOR x EWMA (after warmup) dumps
SLOW_FACTOR: float = float(getenv("MXNET_FLIGHT_SLOW_FACTOR", 4.0))
#: minimum seconds between automatic anomaly dumps (tests set 0)
AUTO_DUMP_MIN_S: float = 30.0

_ALPHA = 0.3       # EWMA smoothing for the watchdog
_WARMUP = 5        # samples before a phase's EWMA can trigger


def enabled() -> bool:
    return ENABLED


def enable() -> None:
    global ENABLED
    ENABLED = True
    # a process started with MXNET_FLIGHT=0 skipped the import-time
    # install; the documented kill -USR2 contract must start holding
    # the moment the recorder is enabled (no-op off the main thread —
    # a later main-thread enable() picks it up)
    _install_signal_handler()


def disable() -> None:
    global ENABLED
    ENABLED = False


# -- ring storage ------------------------------------------------------------
# Record tuple layout (indices are load-bearing for timeline.py):
#   (name, cat, t0_us, t1_us, step, trace_id, labels)
class _Segment:
    """One thread's ring.  Only its owner thread writes; readers
    (dump/summary) snapshot ``buf``/``n`` without a lock — a slot being
    overwritten concurrently yields either the old or the new record,
    never a torn one (list-slot stores are GIL-atomic)."""

    __slots__ = ("tid", "thread_name", "cap", "buf", "n", "epoch",
                 "_thread_ref")

    def __init__(self, tid: int, thread_name: str, cap: int, epoch: int):
        self.tid = tid
        self.thread_name = thread_name
        self.cap = cap
        self.buf: List[Optional[tuple]] = [None] * cap
        self.n = 0          # total records ever written
        self.epoch = epoch
        import weakref
        self._thread_ref = weakref.ref(threading.current_thread())

    @property
    def thread_alive(self) -> bool:
        t = self._thread_ref()
        return t is not None and t.is_alive()

    def add(self, rec: tuple) -> None:
        self.buf[self.n % self.cap] = rec
        self.n += 1

    @property
    def drops(self) -> int:
        return max(0, self.n - self.cap)


_tls = threading.local()
_segments: List[_Segment] = []
_epoch = 0
# registration lock only (once per thread per epoch); rebuilt by
# configure() so sanitizer drills that enable() after import still get
# tracked locks.  REENTRANT on purpose: a signal handler (SIGTERM
# emergency checkpoint) runs flight-instrumented code inline on the
# interrupted thread — if that thread was inside reset()/stats()/
# segment registration holding this lock, a non-reentrant lock would
# self-deadlock the handler (the PR 5 SIGTERM class; same reason the
# SIGUSR2 dump runs on a background thread)
_seg_lock = _san.make_rlock("flight.segments")
_watch_lock = _san.make_lock("flight.watch")
_watch: Dict[str, Tuple[float, int]] = {}   # name -> (ewma_s, count)
# None = no auto-dump yet (the sentinel matters: time.monotonic() can be
# SMALLER than AUTO_DUMP_MIN_S on a freshly booted container, and a 0.0
# seed would then swallow the first anomaly dump — the PR 9 OOM-window
# bug class, fixed in memory.py, reproduced here by
# tests/test_flight.py::test_autodump_rate_limited on this host)
_last_auto_dump: Optional[float] = None
_last_anomaly: dict = {}
_dump_count = 0
_last_dump_path: Optional[str] = None
_trace_counter = itertools.count(1)
_PID_TAG = "%x" % os.getpid()


#: dead-thread segments kept for post-mortem (a worker that died is
#: exactly what a dump should still show); older ones are pruned at
#: registration so thread churn (one prefetcher per epoch, pool
#: restarts) cannot grow _segments — and recorder memory — forever
MAX_DEAD_SEGMENTS = 16


def _segment() -> _Segment:
    seg = getattr(_tls, "seg", None)
    if seg is None or seg.epoch != _epoch:
        from .tracing import _tid
        t = threading.current_thread()
        seg = _Segment(_tid(), t.name, RING, _epoch)
        with _seg_lock:
            dead = [s for s in _segments if not s.thread_alive]
            if len(dead) > MAX_DEAD_SEGMENTS:
                # registration order = age: drop the oldest dead ones
                for s in dead[:len(dead) - MAX_DEAD_SEGMENTS]:
                    _segments.remove(s)
            _segments.append(seg)
        _tls.seg = seg
    return seg


def _now_us() -> float:
    return time.perf_counter() * 1e6


def now_us() -> float:
    """The recorder's clock (perf_counter microseconds) — for call
    sites that span non-lexical scopes and call ``record`` directly."""
    return _now_us()


# -- trace ids ---------------------------------------------------------------
def new_trace_id() -> str:
    """Mint a process-unique request id (lock-free)."""
    return f"{_PID_TAG}-{next(_trace_counter)}"


def current_trace_id() -> Optional[str]:
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def trace_scope(trace_id: Optional[str]):
    """Bind ``trace_id`` to this thread for the scope: records that
    don't pass an explicit id inherit it — how a request's id crosses
    the pad/dispatch/slice phases on the dispatcher thread."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace_id
    try:
        yield
    finally:
        _tls.trace = prev


def join_ids(ids) -> Optional[str]:
    """One scope id for a coalesced group: the single id, or a comma
    join — each member id stays greppable/joinable in the dump."""
    ids = [i for i in ids if i]
    if not ids:
        return None
    return ids[0] if len(ids) == 1 else ",".join(ids)


# -- recording ---------------------------------------------------------------
def record(name: str, cat: str, t0_us: float, t1_us: float,
           step: Optional[int] = None, trace_id: Optional[str] = None,
           labels: Optional[dict] = None, watch: bool = False) -> None:
    """Append one finished phase to this thread's ring.  Timestamps are
    microseconds on the ``time.perf_counter`` clock — the SAME clock
    ``tracing``/``profiler`` events use, so a merged dump orders
    correctly across all three sources."""
    if not ENABLED:
        return
    if trace_id is None:
        trace_id = getattr(_tls, "trace", None)
    _segment().add((name, cat, t0_us, t1_us, step, trace_id, labels))
    if _goodput.ENABLED:
        # one boolean + one dict lookup: top-level unit-of-work spans
        # feed the run's goodput ledger (docs/goodput.md)
        _goodput.observe_span(name, (t1_us - t0_us) / 1e6)
    if watch:
        note(name, (t1_us - t0_us) / 1e6)


def _mem_live():
    """Tracked device bytes from the HBM ledger, or None when the
    ledger is off (lazy import: memory ↔ flight is a benign cycle
    broken by function-level imports on both sides)."""
    from . import memory as _mem
    return _mem.tracked_bytes() if _mem.ENABLED else None


@contextlib.contextmanager
def phase_span(name: str, cat: str = "phase", step: Optional[int] = None,
               trace_id: Optional[str] = None,
               labels: Optional[dict] = None, watch: bool = False,
               mem: bool = False):
    """The flight-recorder primitive: time the body and ring-record it.

    ``MXNET_FLIGHT=0``: ONE boolean test, nothing else.  ``watch=True``
    additionally feeds the slow-phase watchdog (k×EWMA anomaly dump).
    ``mem=True`` samples the HBM ledger's tracked device bytes at entry
    and exit (two O(1) counter reads; skipped when
    ``MXNET_MEMORY_LEDGER=0``) and labels the record with
    ``mem_delta_bytes``/``mem_live_bytes`` — the per-phase memory
    timeline: ``dump()`` renders these as a Perfetto counter track, so
    the timeline shows WHICH phase grew HBM.  The sampled counter is
    PROCESS-global: a concurrent thread allocating inside this span's
    window (e.g. the prefetcher staging the next batch during a
    trainer step) lands in this span's delta too — read overlapping
    spans' deltas together, per-tag truth lives in ``memory.report()``.
    Phase ``name``s must come from a bounded literal set — the
    metrics-hygiene graft-lint rule rejects dynamically built names
    (every distinct name is a forever-entry in ``summary()``).
    """
    if not ENABLED:
        yield
        return
    t0 = _now_us()
    m0 = _mem_live() if mem else None
    try:
        yield
    finally:
        if m0 is not None:
            m1 = _mem_live()
            if m1 is not None:
                labels = dict(labels) if labels else {}
                labels["mem_delta_bytes"] = int(m1 - m0)
                labels["mem_live_bytes"] = int(m1)
        record(name, cat, t0, _now_us(), step=step, trace_id=trace_id,
               labels=labels, watch=watch)


# -- watchdog ----------------------------------------------------------------
def note(name: str, dur_s: float) -> None:
    """Feed one duration sample into ``name``'s EWMA; trigger an
    anomaly dump when it exceeds ``SLOW_FACTOR`` × the warmed EWMA.
    The slow sample still folds into the EWMA afterwards, so a
    *sustained* regime change dumps once and re-adapts instead of
    dumping forever."""
    if not ENABLED:
        return
    anomaly = False
    ewma = 0.0
    with _watch_lock:
        e, c = _watch.get(name, (0.0, 0))
        if c >= _WARMUP and e > 0.0 and dur_s > SLOW_FACTOR * e:
            anomaly, ewma = True, e
        _watch[name] = (dur_s if c == 0 else
                        _ALPHA * dur_s + (1.0 - _ALPHA) * e, c + 1)
    if anomaly:
        _anomaly_dump(name, dur_s, ewma)


def watch_state() -> Dict[str, dict]:
    with _watch_lock:
        return {k: {"ewma_ms": round(e * 1e3, 3), "count": c}
                for k, (e, c) in sorted(_watch.items())}


def watch_ewma(name: str) -> Optional[float]:
    """The warmed EWMA (seconds) of a ``watch=True`` phase, or None
    before ``_WARMUP`` samples.  The training stall watchdog
    (gluon/supervisor.py) seeds its step deadline from the
    ``trainer_step``/``whole_step`` phases through this."""
    with _watch_lock:
        e, c = _watch.get(name, (0.0, 0))
    return e if c >= _WARMUP and e > 0.0 else None


def _anomaly_dump(phase: str, dur_s: float, ewma_s: float) -> None:
    global _last_auto_dump
    now = time.monotonic()
    with _watch_lock:
        if _last_auto_dump is not None and \
                now - _last_auto_dump < AUTO_DUMP_MIN_S:
            return
        _last_auto_dump = now
    _last_anomaly.clear()
    _last_anomaly.update({"phase": phase,
                          "duration_ms": round(dur_s * 1e3, 3),
                          "ewma_ms": round(ewma_s * 1e3, 3),
                          "factor": SLOW_FACTOR})
    # the dump itself (JSON of up to ring-size records) runs OFF the
    # hot path that detected the anomaly — the ring keeps the moments
    # before it regardless of when the writer thread gets scheduled
    threading.Thread(target=_bg_dump, args=("anomaly",),
                     name="mxt-flight-dump", daemon=True).start()


def _bg_dump(reason: str) -> None:
    try:
        path = dump(reason=reason)
        if reason == "anomaly":
            _last_anomaly["path"] = path
        log.warning("flight recorder %s dump: %s (%s)", reason, path,
                    _last_anomaly if reason == "anomaly" else "")
    except Exception as e:  # noqa: BLE001 — a failed dump must not kill
        log.warning("flight recorder %s dump failed: %s", reason, e)


# -- export ------------------------------------------------------------------
def records() -> List[tuple]:
    """Snapshot every live record as ``(segment, record)`` pairs sorted
    by t0 — the raw feed ``timeline``/``summary`` build from."""
    out = []
    with _seg_lock:
        segs = list(_segments)
    for seg in segs:
        n = seg.n
        for r in list(seg.buf[:min(n, seg.cap)] if n <= seg.cap
                      else seg.buf):
            if r is not None:
                out.append((seg, r))
    out.sort(key=lambda p: p[1][2])
    return out


def stats() -> dict:
    with _seg_lock:
        segs = list(_segments)
    written = sum(s.n for s in segs)
    drops = sum(s.drops for s in segs)
    return {"enabled": ENABLED, "ring": RING,
            "records": written - drops, "written": written,
            "drops": drops, "segments": len(segs),
            "dumps": _dump_count, "last_dump": _last_dump_path,
            "last_anomaly": dict(_last_anomaly)}


def dump(path: Optional[str] = None, reason: str = "manual",
         clock=None) -> str:
    """Write the ring (+ the profiler's ``_events``) as Chrome
    trace-event JSON, atomically — open the file in Perfetto / chrome
    about:tracing.  ``path=None`` writes a collision-free timestamped
    file under ``MXNET_FLIGHT_DIR`` (default ``.``); ``clock`` is the
    injectable timestamp source for the filename (tests pin it)."""
    global _dump_count, _last_dump_path
    from . import timeline as _timeline
    from .. import profiler as _prof
    meta = {"reason": reason,
            **({"anomaly": dict(_last_anomaly)} if _last_anomaly else {})}
    if _journal.ENABLED:
        # cross-reference: the dump names its run, the journal names
        # the dump — an operator pivots either way (docs/goodput.md)
        meta["run_id"] = _journal.run_id()
        meta["journal_path"] = _journal.path()
    trace = _timeline.build_trace(records(), list(_prof._events),
                                  meta=meta)
    if path is None:
        d = os.environ.get("MXNET_FLIGHT_DIR", ".") or "."
        os.makedirs(d, exist_ok=True)
        path = unique_path(d, "flight", ".json", clock=clock)
    atomic_write(path, json.dumps(trace))
    _dump_count += 1
    _last_dump_path = path
    from . import metrics as _metrics
    if _metrics.ENABLED:
        # reason is one of {"manual", "anomaly", "signal", "oom",
        # "divergence", "stall", "preempt"} — bounded
        _metrics.FLIGHT_DUMPS.inc(reason=reason)
    if _journal.ENABLED:
        _journal.note_dump(path, reason)
    return path


def summary(top: int = 3) -> dict:
    """Per-phase latency digest of the current ring: count, total,
    p50/p99/max, and the slowest ``top`` records (with step/trace_id —
    the exemplar hop from a bad percentile to a concrete timeline)."""
    from . import timeline as _timeline
    return _timeline.summarize(records(), top=top)


def snapshot_summary() -> dict:
    """The compact block ``observability.snapshot()["flight"]`` carries."""
    out = stats()
    out["phases"] = summary(top=3)
    out["watch"] = watch_state()
    return out


# -- lifecycle ---------------------------------------------------------------
def reset() -> None:
    """Drop every segment/record and the watchdog state (tests).  Other
    threads' next record lands in a fresh segment (epoch bump)."""
    global _epoch, _last_auto_dump
    with _seg_lock:
        _epoch += 1
        _segments.clear()
    with _watch_lock:
        _watch.clear()
    _last_auto_dump = None
    _last_anomaly.clear()


def configure(ring: Optional[int] = None,
              slow_factor: Optional[float] = None) -> None:
    """Re-size the per-thread ring / watchdog factor and reset.  Also
    rebuilds the module locks through the sanitizer factories, so a
    drill that calls ``sanitizer.enable()`` after import gets tracked
    locks (the import-time ones predate it)."""
    global RING, SLOW_FACTOR, _seg_lock, _watch_lock
    if ring is not None:
        RING = max(1, int(ring))
    if slow_factor is not None:
        SLOW_FACTOR = float(slow_factor)
    _seg_lock = _san.make_rlock("flight.segments")
    _watch_lock = _san.make_lock("flight.watch")
    reset()


# -- SIGUSR2: dump on demand --------------------------------------------------
_signal_installed = False


def _install_signal_handler() -> None:
    """kill -USR2 <pid> → flight dump (production escape hatch: grab a
    timeline from a live process without attaching anything).  Chains a
    pre-existing handler; installs at most once (re-invoked by
    ``enable()`` for MXNET_FLIGHT=0 starts); silently unavailable off
    the main thread or on platforms without SIGUSR2."""
    global _signal_installed
    if not ENABLED or _signal_installed:
        return
    try:
        import signal
        if threading.current_thread() is not threading.main_thread():
            return
        prev = signal.getsignal(signal.SIGUSR2)

        def _on_usr2(signum, frame):
            # the dump runs on a BACKGROUND thread, never inline: the
            # handler executes between bytecodes of the interrupted
            # main thread, which may already hold _seg_lock or the
            # metrics mutation lock — an inline dump() would then
            # self-deadlock the whole process on a non-reentrant lock
            try:
                threading.Thread(target=_bg_dump, args=("signal",),
                                 name="mxt-flight-dump",
                                 daemon=True).start()
            except Exception:  # noqa: BLE001 — never die in a handler
                pass
            if callable(prev):
                prev(signum, frame)

        signal.signal(signal.SIGUSR2, _on_usr2)
        _signal_installed = True
    except (ValueError, OSError, AttributeError):
        pass


_install_signal_handler()
