"""Goodput ledger: classify every second of a run into a fixed badput
taxonomy (ISSUE 16).

PRs 1/8/9/13 can see inside one step — phases, HBM, flops, MFU — but
none of them answers the operator's fleet question: *what fraction of
this run's wall-clock was useful training*, and where did the rest go?
This module keeps that account.  Every second of a training or serving
run is attributed to exactly one class of a small, fixed taxonomy:

  ==================  =====================================================
  ``compute``         useful work — flight's ``trainer_step`` /
                      ``whole_step`` / ``serve_dispatch`` spans
  ``data_wait``       input starvation — prefetch/batch-wait spans
  ``checkpoint_block``  synchronous checkpoint save time
  ``retry_replay``    supervisor snapshot-restore + window replay after
                      a transient step failure
  ``rewind``          supervisor divergence rewind (restore to the last
                      finite-loss snapshot)
  ``recompile``       XLA compile time (serving precompile measured;
                      training ``note_program`` counted)
  ``eviction_churn``  multi-model registry evict/readmit work
  ``stall``           wedged-device time the stall watchdog declared
  ``shed``            serving work refused/expired under pressure
  ``unattributed``    wall-clock no instrument claimed (the honesty row
                      — acceptance keeps it ≤ 5% under chaos)
  ==================  =====================================================

Attribution is passive: ``flight.record()`` taps every completed span
into ``observe_span`` (one dict lookup on the hot path), supervisors
bracket their replay loops in ``replay_scope``, and discrete badput
events call ``attribute(reason, seconds)``.  ``report()`` renders the
per-class seconds + goodput %, ``metrics`` exports
``mxnet_goodput_ratio`` / ``mxnet_badput_seconds_total{reason}``, and
``timeline.py`` draws the cumulative badput counter track in Perfetto.

SLO burn monitors ride the same ledger: declared targets
(``MXNET_SLO_GOODPUT_PCT``, ``MXNET_SLO_SERVE_P99_MS``) are evaluated
over sliding windows and fire rate-limited warnings +
``mxnet_slo_burn_total{slo}`` + a failed ``slo_burn`` readyz() check on
``ResilientServer`` (serving/resilience.py), journaled like every other
lifecycle event.

``MXNET_GOODPUT=0`` reduces every hook to one module-global boolean
test (the PR 1 contract, machine-checked by the gate-hygiene lint).
"""
from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..base import getenv
from ..analysis.sanitizer import make_lock

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "CLASSES", "observe_span", "attribute",
           "note_event", "replay_scope", "report", "start",
           "serve_latency_sample", "slo_state", "slo_burning",
           "maybe_fire_slo", "enable", "disable", "enabled",
           "configure", "reset",
           "SLO_GOODPUT_PCT", "SLO_SERVE_P99_MS"]

#: kill-switch (docs/env_var.md); parsed once — the gate contract
ENABLED: bool = bool(getenv("MXNET_GOODPUT", True))

#: the complete, closed taxonomy — ``attribute`` folds anything else
#: into ``unattributed`` (warn-once) instead of growing the ledger
CLASSES = ("compute", "data_wait", "checkpoint_block", "retry_replay",
           "rewind", "recompile", "eviction_churn", "stall", "shed",
           "unattributed")

#: badput classes exported as ``mxnet_badput_seconds_total{reason}``
#: (compute is goodput; unattributed is derived, not accumulated)
_BADPUT_CLASSES = frozenset(CLASSES) - {"compute", "unattributed"}

#: flight span name -> taxonomy class.  Only TOP-LEVEL unit-of-work
#: spans appear here — nested phases (h2d/allreduce/fused_update inside
#: trainer_step) must NOT, or their seconds would double-count.
_SPAN_CLASS: Dict[str, str] = {
    "trainer_step": "compute",
    "whole_step": "compute",
    "superstep": "compute",
    "serve_dispatch": "compute",
    "prefetch_wait": "data_wait",
    "data_wait": "data_wait",
    "checkpoint_block": "checkpoint_block",
    "serve_evict": "eviction_churn",
    "serve_readmit": "eviction_churn",
}

# --- SLO targets (0 = monitor off; deliberately NOT gate-shaped) -----------
#: minimum acceptable goodput % over the run (e.g. 90.0)
SLO_GOODPUT_PCT: float = getenv("MXNET_SLO_GOODPUT_PCT", 0.0)
#: maximum acceptable serving p99 latency in ms over the sliding window
SLO_SERVE_P99_MS: float = getenv("MXNET_SLO_SERVE_P99_MS", 0.0)
#: sliding-window size for serve latency p99
SLO_WINDOW: int = 256
#: don't judge p99 on fewer samples than this
SLO_MIN_SAMPLES: int = 20
#: minimum seconds between burn firings per slo (tests set 0) — the
#: never-spam posture of flight.AUTO_DUMP_MIN_S / POST_MORTEM_MIN_S
SLO_BURN_MIN_S: float = 30.0
#: goodput SLO needs some run under its belt before it can burn
SLO_MIN_RUN_S: float = 5.0

_lock = make_lock("goodput.ledger")
_ledger: Dict[str, Dict[str, float]] = {}
_events: Dict[str, int] = {}
# run clock origin (time.monotonic); lazily set on first attribution so
# an idle import doesn't start the meter, explicitly set by start()
_run_started: Optional[float] = None
# process-global (NOT thread-local: the supervisor may run step_fn on a
# watchdog worker thread while replay_scope is held on the caller)
_replay_depth: int = 0
_warned_unknown: set = set()

# SLO state: sliding serve-latency window + rate-limit timestamps.
# None sentinels, never 0.0 — time.monotonic() can be < SLO_BURN_MIN_S
# on a freshly booted container (the PR 9 lesson).
_serve_lat_ms: deque = deque(maxlen=SLO_WINDOW)
_slo_last_fire: Dict[str, Optional[float]] = {}
_slo_burning: Dict[str, bool] = {}


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------
def _touch_clock_locked(now: float) -> None:
    global _run_started
    if _run_started is None:
        _run_started = now


def start() -> None:
    """Pin the run-clock origin to *now* (callers that want wall-clock
    accounting from a known point — the chaos test, bench rider, or a
    training driver's first step).  Without it the clock starts at the
    first attributed span."""
    if not ENABLED:
        return
    global _run_started
    with _lock:
        _run_started = time.monotonic()


def observe_span(name: str, dur_s: float) -> None:
    """Hot-path tap from ``flight.record()``: fold a completed span into
    the ledger when its name is a recognized unit of work.  One dict
    lookup for unrecognized names; compute spans recorded *during* a
    replay_scope are skipped (the scope already owns that wall-clock —
    counting both would book replayed steps as goodput)."""
    if not ENABLED:
        return
    cls = _SPAN_CLASS.get(name)
    if cls is None or dur_s <= 0.0:
        return
    if cls == "compute" and _replay_depth > 0:
        return
    now = time.monotonic()
    with _lock:
        _touch_clock_locked(now)
        b = _ledger.get(cls)
        if b is None:
            b = _ledger[cls] = {"seconds": 0.0, "events": 0}
        b["seconds"] += dur_s
        b["events"] += 1


def attribute(reason: str, seconds: float) -> None:
    """Book ``seconds`` of wall-clock against taxonomy class ``reason``
    (discrete badput events: stall timeouts, shed requests, measured
    compile time).  Unknown reasons fold into ``unattributed`` with a
    one-shot warning — the taxonomy is closed by design, and the
    graft-lint metrics-hygiene rule flags dynamically built reason
    strings at the call site."""
    if not ENABLED:
        return
    if reason not in CLASSES:
        if reason not in _warned_unknown:
            _warned_unknown.add(reason)
            log.warning("goodput.attribute: unknown class %r folded "
                        "into 'unattributed' (taxonomy: %s)",
                        reason, ", ".join(CLASSES))
        reason = "unattributed"
    if seconds < 0.0:
        seconds = 0.0
    now = time.monotonic()
    with _lock:
        _touch_clock_locked(now)
        b = _ledger.get(reason)
        if b is None:
            b = _ledger[reason] = {"seconds": 0.0, "events": 0}
        b["seconds"] += seconds
        b["events"] += 1
    if reason in _BADPUT_CLASSES and seconds > 0.0:
        try:
            from . import metrics as _metrics
            if _metrics.ENABLED:
                _metrics.BADPUT_SECONDS.inc(seconds, reason=reason)
        except Exception:  # noqa: BLE001 — accounting must not raise
            pass


def note_event(reason: str) -> None:
    """Count a taxonomy event whose duration is unknown (training
    ``note_program`` recompiles: the compile happened inside jax, we
    only see the notification).  Shows up in ``report()['events']``
    without inventing seconds."""
    if not ENABLED:
        return
    with _lock:
        _events[reason] = _events.get(reason, 0) + 1


@contextlib.contextmanager
def replay_scope(reason: str):
    """Bracket a supervisor restore+replay (``retry_replay``) or
    divergence rewind (``rewind``): the scope's own wall-clock is
    attributed to ``reason``, and compute spans recorded while ANY scope
    is open are suppressed so replayed steps don't double-book as
    goodput.  Process-global on purpose — the supervisor can execute
    the replayed step_fn on a watchdog worker thread."""
    if not ENABLED:
        yield
        return
    global _replay_depth
    t0 = time.monotonic()
    with _lock:
        _replay_depth += 1
    try:
        yield
    finally:
        dt = time.monotonic() - t0
        with _lock:
            _replay_depth -= 1
        attribute(reason, dt)


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------
def report() -> dict:
    """The goodput account: ``{"classes": {cls: {"seconds", "events"}},
    "events": {...}, "wall_s", "attributed_s", "unattributed_s",
    "goodput_pct", "unattributed_pct"}``.  ``unattributed`` is derived
    — wall-clock since the run-clock origin minus everything the
    instruments claimed — so it is the honesty row: a big number here
    means a subsystem is running untraced."""
    if not ENABLED:
        return {"enabled": False}
    now = time.monotonic()
    with _lock:
        classes = {c: dict(b) for c, b in _ledger.items()}
        events = dict(_events)
        started = _run_started
    attributed = sum(b["seconds"] for b in classes.values())
    wall = max(0.0, now - started) if started is not None else 0.0
    # a fast instrumented burst can attribute more than the coarse wall
    # clock (span overlap); clamp instead of reporting negative slack
    wall = max(wall, attributed)
    unattributed = max(0.0, wall - attributed)
    compute = classes.get("compute", {}).get("seconds", 0.0)
    goodput_pct = (100.0 * compute / wall) if wall > 0 else 0.0
    unattr_pct = (100.0 * unattributed / wall) if wall > 0 else 0.0
    return {"enabled": True, "classes": classes, "events": events,
            "wall_s": wall, "attributed_s": attributed,
            "unattributed_s": unattributed,
            "goodput_pct": goodput_pct,
            "unattributed_pct": unattr_pct}


def ratio() -> float:
    """goodput fraction in [0, 1] (the ``mxnet_goodput_ratio`` gauge);
    0.0 before any attribution."""
    if not ENABLED:
        return 0.0
    r = report()
    return r["goodput_pct"] / 100.0 if r.get("enabled") else 0.0


def badput_totals() -> Dict[str, float]:
    """Cumulative seconds per badput class (timeline counter track)."""
    if not ENABLED:
        return {}
    with _lock:
        return {c: b["seconds"] for c, b in _ledger.items()
                if c != "compute"}


# ---------------------------------------------------------------------------
# SLO burn monitors
# ---------------------------------------------------------------------------
def serve_latency_sample(ms: float) -> None:
    """Feed one end-to-end serve latency into the sliding p99 window
    (called from the ResilientServer dispatch loop) and evaluate the
    serve SLO."""
    if not ENABLED:
        return
    with _lock:
        _serve_lat_ms.append(ms)
    if SLO_SERVE_P99_MS > 0.0:
        maybe_fire_slo("serve_p99")


def _serve_p99_locked() -> Optional[float]:
    if len(_serve_lat_ms) < SLO_MIN_SAMPLES:
        return None
    xs = sorted(_serve_lat_ms)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]


def slo_state() -> dict:
    """Declared targets + current measurements + burn flags, for
    ``snapshot()["goodput"]["slo"]`` and the readyz detail row."""
    if not ENABLED:
        return {}
    with _lock:
        p99 = _serve_p99_locked()
        n = len(_serve_lat_ms)
        burning = dict(_slo_burning)
    out: dict = {}
    if SLO_GOODPUT_PCT > 0.0:
        out["goodput"] = {"target_pct": SLO_GOODPUT_PCT,
                          "actual_pct": report().get("goodput_pct"),
                          "burning": burning.get("goodput", False)}
    if SLO_SERVE_P99_MS > 0.0:
        out["serve_p99"] = {"target_ms": SLO_SERVE_P99_MS,
                            "actual_ms": p99, "samples": n,
                            "burning": burning.get("serve_p99", False)}
    return out


def _evaluate(slo: str) -> Optional[bool]:
    """Is ``slo`` currently violated?  None == not enough signal."""
    if slo == "serve_p99":
        with _lock:
            p99 = _serve_p99_locked()
        if p99 is None:
            return None
        return p99 > SLO_SERVE_P99_MS
    if slo == "goodput":
        r = report()
        if r.get("wall_s", 0.0) < SLO_MIN_RUN_S:
            return None
        return r["goodput_pct"] < SLO_GOODPUT_PCT
    return None


def maybe_fire_slo(slo: str) -> bool:
    """Evaluate one SLO; on breach set its burning flag and (rate-
    limited by ``SLO_BURN_MIN_S``) warn + ``mxnet_slo_burn_total{slo}``
    + journal a ``slo_burn`` entry.  Returns the burning state.  The
    flag clears as soon as an evaluation passes — readyz() reflects the
    live window, not history."""
    if not ENABLED:
        return False
    violated = _evaluate(slo)
    if violated is None:
        return _slo_burning.get(slo, False)
    with _lock:
        _slo_burning[slo] = violated
        if not violated:
            return False
        now = time.monotonic()
        last = _slo_last_fire.get(slo)
        if last is not None and now - last < SLO_BURN_MIN_S:
            return True
        _slo_last_fire[slo] = now
    detail = slo_state().get(slo, {})
    log.warning("SLO BURN (%s): %s", slo, detail)
    try:
        from . import metrics as _metrics
        if _metrics.ENABLED:
            _metrics.SLO_BURN.inc(slo=slo)
    except Exception:  # noqa: BLE001
        pass
    try:
        from . import journal as _journal
        if _journal.ENABLED:
            _journal.emit("slo_burn", durable=True, slo=slo, **detail)
    except Exception:  # noqa: BLE001
        pass
    return True


def slo_burning() -> bool:
    """Any SLO currently burning?  (the readyz() ``slo_burn`` check —
    re-evaluates the goodput SLO lazily since nothing else polls it)."""
    if not ENABLED:
        return False
    if SLO_GOODPUT_PCT > 0.0:
        maybe_fire_slo("goodput")
    return any(_slo_burning.values())


def slo_armed() -> bool:
    """Is any SLO target declared?  (readyz only lists the check when
    an operator opted in)."""
    if not ENABLED:
        return False
    return SLO_GOODPUT_PCT > 0.0 or SLO_SERVE_P99_MS > 0.0


# ---------------------------------------------------------------------------
# toggles + test plumbing
# ---------------------------------------------------------------------------
def enable() -> None:
    """Turn the ledger on at runtime (overrides MXNET_GOODPUT=0)."""
    global ENABLED
    ENABLED = True


def disable() -> None:
    global ENABLED
    ENABLED = False


def enabled() -> bool:
    return ENABLED


def configure(slo_goodput_pct: Optional[float] = None,
              slo_serve_p99_ms: Optional[float] = None,
              slo_burn_min_s: Optional[float] = None,
              slo_min_samples: Optional[int] = None,
              slo_min_run_s: Optional[float] = None) -> None:
    """Override SLO targets/rate-limits at runtime (tests, notebooks)."""
    global SLO_GOODPUT_PCT, SLO_SERVE_P99_MS, SLO_BURN_MIN_S
    global SLO_MIN_SAMPLES, SLO_MIN_RUN_S
    if slo_goodput_pct is not None:
        SLO_GOODPUT_PCT = float(slo_goodput_pct)
    if slo_serve_p99_ms is not None:
        SLO_SERVE_P99_MS = float(slo_serve_p99_ms)
    if slo_burn_min_s is not None:
        SLO_BURN_MIN_S = float(slo_burn_min_s)
    if slo_min_samples is not None:
        SLO_MIN_SAMPLES = int(slo_min_samples)
    if slo_min_run_s is not None:
        SLO_MIN_RUN_S = float(slo_min_run_s)


def reset() -> None:
    """Zero the ledger, run clock, and SLO state (tests)."""
    global _run_started, _replay_depth
    with _lock:
        _ledger.clear()
        _events.clear()
        _run_started = None
        _replay_depth = 0
        _warned_unknown.clear()
        _serve_lat_ms.clear()
        _slo_last_fire.clear()
        _slo_burning.clear()
