"""Crash-durable run journal: append-only JSONL lifecycle log (ISSUE 16).

Every telemetry surface built so far — metrics registry, flight ring,
HBM ledger, program table — lives in process memory and dies with the
process.  That is precisely backwards for the events an operator needs
*after* a crash: why did the run end, what was the last completed step,
which checkpoint is the resume point, how often did the supervisor
rewind.  This module is the survivor: a single append-only
``journal.jsonl`` under ``MXNET_RUN_DIR`` where each lifecycle event is
one self-contained JSON line written with a single ``write()`` call
(atomic at the OS level for sane line sizes) and — for *durable* events
(checkpoint saves, post-mortems, terminal preemption entries) —
``fsync``'d before the caller proceeds, so a SIGKILL one instruction
later still leaves the entry on disk.

Design points:

  * **Run-id continuity across restart.** The first process to open the
    journal mints ``run-<epoch>-<pidhex>`` and writes a
    ``process_start`` entry; a restarted process finds the existing
    ``journal.jsonl``, reads the run id from its first line, and keeps
    appending under the same id — so goodput accounting and the offline
    reporter see preemption→resume as one run with two incarnations.
  * **Never raises.** Journaling is observability, not control flow: a
    full disk degrades to a warning, not a dead training loop.
  * **Rotation-capped.** At ``MAX_BYTES`` the file shifts to
    ``journal.1.jsonl`` (one generation kept) and a fresh segment
    re-records the run header, so a runaway event source cannot eat the
    disk.
  * **Gate contract.** ``ENABLED`` is derived once at import from
    ``MXNET_RUN_DIR``; every hook in other modules reduces to
    ``if _journal.ENABLED:`` — one boolean, no env re-reads (PR 1).

The offline consumer is ``python -m mxnet_tpu.observability.report``
(see ``report.py`` / docs/goodput.md).
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import IO, Optional

from ..base import getenv
from ..analysis.sanitizer import make_lock

log = logging.getLogger(__name__)

__all__ = ["ENABLED", "RUN_DIR", "emit", "run_id", "path", "note_dump",
           "resume_marker", "maybe_milestone", "configure", "reset",
           "FILE_NAME", "MAX_BYTES", "MILESTONE_EVERY"]

#: run directory; empty string == journaling off.  Read ONCE at import —
#: the journal is a process-lifetime artifact, not a per-call toggle.
RUN_DIR: str = getenv("MXNET_RUN_DIR", "")

#: the one-boolean gate every cross-module hook tests (PR 1 contract).
#: Deliberately derived from RUN_DIR rather than a dedicated bool env:
#: "journaling on" and "where the journal lives" are the same fact.
ENABLED: bool = bool(RUN_DIR)

#: journal segment filename inside the run dir
FILE_NAME = "journal.jsonl"

#: rotate the active segment past this size (one prior generation kept)
MAX_BYTES: int = 64 * 1024 * 1024

#: step milestones are recorded every N steps per source (tests set 1)
MILESTONE_EVERY: int = 25

_lock = make_lock("journal.file")
_fh: Optional[IO[str]] = None
_run_id: Optional[str] = None
_bytes: int = 0
# per-source last-milestone step, so trainer/wholestep/supervisor each
# get their own cadence without double-recording the same step
_milestone_at: dict = {}


# ---------------------------------------------------------------------------
# open / run-id continuity
# ---------------------------------------------------------------------------
def _read_existing_run_id(fpath: str) -> Optional[str]:
    """Recover the run id from an existing journal's first valid line —
    a torn tail (SIGKILL mid-write) must not break resumption, so every
    line is parsed tolerantly until one carries ``run``."""
    try:
        with open(fpath, "r", encoding="utf-8") as f:
            for raw in f:
                try:
                    rid = json.loads(raw).get("run")
                except Exception:  # noqa: BLE001 — torn line, keep scanning
                    continue
                if rid:
                    return str(rid)
    except OSError:
        return None
    return None


def _open_locked() -> Optional[IO[str]]:
    """Open (creating) the active journal segment; mint or resume the
    run id.  Caller holds ``_lock``."""
    global _fh, _run_id, _bytes
    if _fh is not None:
        return _fh
    if not ENABLED:
        return None
    try:
        os.makedirs(RUN_DIR, exist_ok=True)
        fpath = os.path.join(RUN_DIR, FILE_NAME)
        existing = _read_existing_run_id(fpath)
        resumed = existing is not None
        if resumed:
            _run_id = existing
        else:
            _run_id = "run-%d-%x" % (int(time.time()), os.getpid())
        # append-only ON PURPOSE: the journal's durability unit is one
        # LINE (single write() + fsync), not the file — atomic_write's
        # tmp+rename would wipe prior incarnations' entries, the exact
        # history the journal exists to keep.  A torn tail line is
        # expected after SIGKILL and every reader skips it
        # (_read_existing_run_id, report.py).
        # graft-lint: disable=atomic-write
        _fh = open(fpath, "a", encoding="utf-8")
        _bytes = _fh.tell()
        _write_locked({"event": "process_start", "run": _run_id,
                       "t": time.time(), "pid": os.getpid(),
                       "resumed": resumed}, durable=True)
    except Exception as e:  # noqa: BLE001 — journal must never kill the run
        log.warning("run journal open failed (%s): %s", RUN_DIR, e)
        _fh = None
        _run_id = None
    return _fh


def _rotate_locked() -> None:
    """Shift the active segment to ``journal.1.jsonl`` and start fresh
    (re-recording the run header so each segment is self-describing)."""
    global _fh, _bytes
    if _fh is None:
        return
    try:
        _fh.close()
    except Exception:  # noqa: BLE001
        pass
    _fh = None
    fpath = os.path.join(RUN_DIR, FILE_NAME)
    old = os.path.join(RUN_DIR, "journal.1.jsonl")
    try:
        os.replace(fpath, old)
    except OSError as e:
        log.warning("journal rotation failed: %s", e)
    try:
        _fh = open(fpath, "a", encoding="utf-8")
        _bytes = 0
        _write_locked({"event": "rotated", "run": _run_id,
                       "t": time.time(), "pid": os.getpid()},
                      durable=True)
    except Exception as e:  # noqa: BLE001
        log.warning("journal reopen after rotation failed: %s", e)
        _fh = None


def _write_locked(entry: dict, durable: bool = False) -> None:
    """Serialize + append one line; fsync when durable.  Caller holds
    ``_lock`` and guarantees ``_fh`` is open."""
    global _bytes
    line = json.dumps(entry, default=str, separators=(",", ":")) + "\n"
    _fh.write(line)
    _fh.flush()
    if durable:
        os.fsync(_fh.fileno())
    _bytes += len(line)
    if _bytes > MAX_BYTES:
        _rotate_locked()


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
def emit(event: str, step: Optional[int] = None, durable: bool = False,
         **fields) -> Optional[dict]:
    """Append one journal entry: ``{"event", "run", "t", "pid",
    ["step"], **fields}``.  ``durable=True`` fsyncs before returning —
    reserve it for lifecycle events (saves, post-mortems, terminal
    entries); milestones ride the page cache.  Never raises; returns the
    entry dict (tests) or ``None`` when disabled/failed.

    ``event`` must be a bounded literal name — dynamically built event
    names are flagged by the graft-lint metrics-hygiene rule (unbounded
    journal cardinality); put variability in ``fields``.
    """
    if not ENABLED:
        return None
    try:
        with _lock:
            if _open_locked() is None:
                return None
            entry = {"event": event, "run": _run_id, "t": time.time(),
                     "pid": os.getpid()}
            if step is not None:
                entry["step"] = int(step)
            entry.update(fields)
            _write_locked(entry, durable=durable)
            return entry
    except Exception as e:  # noqa: BLE001 — never let the journal kill a run
        log.warning("journal emit(%s) failed: %s", event, e)
        return None


def run_id() -> Optional[str]:
    """The active run id (minted or resumed), ``None`` when disabled."""
    if not ENABLED:
        return None
    with _lock:
        _open_locked()
        return _run_id


def path() -> Optional[str]:
    """Absolute path of the active journal segment, ``None`` when
    disabled — what post-mortems embed so an operator can pivot from a
    crash report to the run timeline."""
    if not ENABLED:
        return None
    return os.path.abspath(os.path.join(RUN_DIR, FILE_NAME))


def note_dump(dump_path: Optional[str], reason: str) -> None:
    """Cross-reference a flight/post-mortem dump file in the journal
    (ISSUE 16 satellite: journal rows carry dump filenames and dumps
    carry the run id — pivotable both ways)."""
    if not ENABLED or not dump_path:
        return
    emit("flight_dump", durable=False, dump_path=dump_path, why=reason)


def resume_marker(step: int, source: str = "checkpoint", **fields) -> None:
    """Record that a restarted process re-entered training at ``step``
    (called from ``restore_trainer``/``restore_or_initialize``) — the
    durable stitch between incarnations of one run."""
    if not ENABLED:
        return
    emit("run_resumed", step=step, durable=True, source=source, **fields)


def maybe_milestone(step: int, source: str, **fields) -> None:
    """Record a step milestone every ``MILESTONE_EVERY`` steps per
    source, annotated with the live goodput summary when available.
    Non-durable (milestones are recoverable by replay; fsync here would
    tax the hot loop)."""
    if not ENABLED:
        return
    last = _milestone_at.get(source)
    if last is not None and step - last < MILESTONE_EVERY:
        return
    _milestone_at[source] = step
    try:
        from . import goodput as _goodput
        if _goodput.ENABLED:
            g = _goodput.report()
            fields.setdefault("goodput_pct", g.get("goodput_pct"))
            fields.setdefault("classes", g.get("classes"))
    except Exception:  # noqa: BLE001 — milestone stays useful without goodput
        pass
    emit("milestone", step=step, durable=False, source=source, **fields)


# ---------------------------------------------------------------------------
# test plumbing
# ---------------------------------------------------------------------------
def configure(run_dir: Optional[str] = None) -> None:
    """Re-point the journal (tests): closes the active segment, resets
    run-id/milestone state, and re-derives ``ENABLED`` from the new
    directory (empty string disables)."""
    global RUN_DIR, ENABLED
    reset()
    if run_dir is not None:
        RUN_DIR = run_dir
        ENABLED = bool(run_dir)


def reset() -> None:
    """Close the journal and drop in-memory state (tests).  The file on
    disk is left alone — that is the whole point of the journal."""
    global _fh, _run_id, _bytes
    with _lock:
        if _fh is not None:
            try:
                _fh.close()
            except Exception:  # noqa: BLE001
                pass
        _fh = None
        _run_id = None
        _bytes = 0
        _milestone_at.clear()
