"""Serving resilience tier: admission control, deadline-aware load
shedding, health/readiness.

The layer above `BucketedPredictor`/`MicroBatcher` that millions of
users actually need: under overload a serving replica must degrade to
**bounded p99 plus typed rejections**, never tail-latency collapse.
The design follows the classic production-serving playbook (TF-Serving
/ SRE shape, the arxiv 1605.08695 health-checked-worker argument):

  * **admission control** — bounded per-tenant priority queues; a full
    queue rejects with a typed `Overloaded` carrying a retry-after
    hint (`MXNET_SERVE_MAX_QUEUE`).
  * **load shedding** — with `MXNET_SERVE_SHED_POLICY=deadline`
    (default) a request whose deadline the estimated service time
    already cannot meet is shed AT SUBMIT — rejecting in microseconds
    beats queueing work that will expire anyway.
  * **deadline-aware scheduling** — the dispatcher pops highest
    priority, earliest deadline first (round-robin across tenants so
    one noisy tenant cannot starve the rest) and drops already-expired
    work BEFORE padding/dispatch (typed `DeadlineExceeded`; the
    `expired_dispatches` stat pins "expired work is never dispatched"
    at zero).
  * **health/readiness** — `healthz()` (liveness: threads up) and
    `readyz()` (traffic-worthiness: warmup complete, compile cache
    wired, dispatch latency / failure rate / stall within thresholds,
    hot-reload freshness), evaluated by a watchdog thread and surfaced
    through the metrics registry (`mxnet_serve_ready`,
    `mxnet_serve_ready_transitions_total`,
    `snapshot()["serving"]["ready"]`).

Failure behavior is testable: `mxnet_tpu.faultinject` injects
delays/raises at the dispatch site so chaos tests can prove bounded
queues and >= 90% goodput under 2x flood (tests/test_resilience.py,
docs/serving_resilience.md).
"""
from __future__ import annotations

import heapq
import itertools
import logging
import math
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from contextlib import nullcontext as _nullcontext
from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..analysis import hot_path, sanitizer as _san
from ..base import MXNetError, getenv
from ..observability import flight as _flight
from ..observability import goodput as _goodput
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from .batcher import (BatcherClosedError, BatcherDeadError,
                      group_trace_scope, record_group_queue_wait,
                      stack_requests)

log = logging.getLogger(__name__)

__all__ = ["Overloaded", "DeadlineExceeded", "ResilientServer",
           "SHED_POLICIES", "StepEDF"]

SHED_POLICIES = ("depth", "deadline")


class Overloaded(MXNetError):
    """Request rejected by admission control (reject-with-backpressure).

    ``retry_after_s`` is the server's estimate of when capacity frees
    up — an RPC front end maps it to ``Retry-After`` so well-behaved
    clients back off instead of hammering a saturated replica."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(MXNetError):
    """An admitted request's deadline passed while it waited in queue.
    The work was dropped BEFORE padding/dispatch — the accelerator
    never burns a cycle on an answer nobody is waiting for."""


class StepEDF:
    """Earliest-deadline-first estimator at DECODE-STEP granularity —
    the generative twin of `_estimate_wait_s`'s whole-request EWMA.

    A generation's cost is `remaining tokens x per-step seconds`, not
    one dispatch, so request-level deadline shedding either admits
    hopeless sequences (burning decode steps on answers that will
    expire) or sheds meetable ones.  `DecodeEngine` feeds every step's
    wall-clock into the EWMA and asks two questions: at ADMISSION,
    whether the deadline clears the ETA behind the queued token
    backlog; BETWEEN STEPS, whether an in-flight sequence's remaining
    tokens still fit before its deadline (`unmeetable` — preempted
    typed only when admitted work is waiting to take the slot)."""

    #: conservative prior before any observation (CPU-ish step cost);
    #: EWMA converges within ~10 steps either direction
    PRIOR_S = 0.01

    def __init__(self, alpha: float = 0.2):
        self._alpha = float(alpha)
        self._ewma: Optional[float] = None

    def observe(self, step_s: float) -> None:
        """Fold one measured decode-step wall-clock into the EWMA."""
        step_s = max(0.0, float(step_s))
        self._ewma = step_s if self._ewma is None else \
            (1 - self._alpha) * self._ewma + self._alpha * step_s

    def step_s(self) -> float:
        """Current per-decode-step estimate (prior until observed)."""
        return self.PRIOR_S if self._ewma is None else self._ewma

    def eta_s(self, tokens: int, lanes: int = 1) -> float:
        """Estimated seconds to decode `tokens` more tokens with
        `lanes` slots advancing one token per step each."""
        return (max(0, int(tokens)) / max(1, int(lanes))) * self.step_s()

    def unmeetable(self, deadline: Optional[float], now: float,
                   remaining_tokens: int) -> bool:
        """True when `remaining_tokens` more steps cannot finish before
        `deadline` (absolute perf_counter time; None = no deadline)."""
        if deadline is None:
            return False
        return now + self.eta_s(remaining_tokens) > deadline


class _Request:
    __slots__ = ("inputs", "rows", "future", "tenant", "tref",
                 "priority", "deadline", "t0", "trace_id")

    def __init__(self, inputs, tenant: str, priority: int,
                 deadline: Optional[float]):
        self.inputs = inputs
        self.rows = next(iter(inputs.values())).shape[0]
        self.future: Future = Future()
        self.tenant = tenant
        # direct _Tenant reference (set at admission): accounting after
        # pop must not look the name up again — idle-tenant eviction
        # may have removed it from the table by then
        self.tref: Optional["_Tenant"] = None
        self.priority = int(priority)
        self.deadline = deadline  # absolute perf_counter time, or None
        self.t0 = time.perf_counter()
        # flight-recorder id: one per request, end to end (admission ->
        # queue-wait -> pad -> dispatch -> slice across threads)
        self.trace_id = _flight.new_trace_id() if _flight.ENABLED \
            else None


class _Tenant:
    __slots__ = ("name", "heap", "rows_queued", "admitted", "served",
                 "expired", "shed")

    def __init__(self, name: str):
        self.name = name
        # entries: (-priority, deadline_or_inf, seq, request) — pops
        # highest priority first, earliest deadline within a priority
        self.heap: List[Tuple] = []
        self.rows_queued = 0
        self.admitted = 0
        self.served = 0
        self.expired = 0
        self.shed = 0


class ResilientServer:
    """Admission-controlled, deadline-aware front for a
    ``BucketedPredictor``.

    Parameters
    ----------
    predictor : BucketedPredictor
        The AOT-compiled serving executor requests route through.
    max_queue : int
        Per-tenant bound on queued requests (default
        ``MXNET_SERVE_MAX_QUEUE``, 64).  The hard backpressure line:
        beyond it ``submit`` raises ``Overloaded``.
    shed_policy : str
        ``"depth"`` = only the queue bound sheds; ``"deadline"``
        (default, ``MXNET_SERVE_SHED_POLICY``) additionally sheds a
        deadlined request whose estimated wait already exceeds its
        deadline.
    max_wait_ms / max_batch : float / int
        Coalescing knobs, same semantics as ``MicroBatcher``
        (``MXNET_SERVE_MAX_WAIT_MS`` / largest batch bucket).
    unready_latency_ms : float, optional
        Watchdog threshold: dispatch-latency EWMA above this marks the
        replica unready (None/0 disables).
    unready_failure_rate : float
        Watchdog threshold on the failure fraction of the last
        ``window`` dispatches (default 0.5).
    stall_timeout_s : float
        Work queued but no dispatch completed for this long marks
        unready (a hung backend looks exactly like this).
    reload_staleness_s : float, optional
        When the predictor runs ``start_auto_reload``, an unsuccessful
        polling streak longer than this marks unready (default: 3x the
        reload interval; None disables).
    max_tenants : int
        Bound on distinct tenant names (default 256).  ``tenant`` is a
        CLIENT CLASS (service, priority tier), not a per-user id —
        every distinct name costs a queue, a round-robin slot, and
        per-tenant metric series, and admission scans are O(tenants).
        Past the bound, idle tenants (empty queue) are evicted to make
        room; if every tenant is busy the submit raises ``Overloaded``.
    """

    def __init__(self, predictor, max_queue: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 max_wait_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 watchdog_interval_s: float = 0.25,
                 unready_latency_ms: Optional[float] = None,
                 unready_failure_rate: float = 0.5,
                 stall_timeout_s: float = 10.0,
                 reload_staleness_s: Optional[float] = None,
                 max_tenants: int = 256,
                 extra_ready=None, oom_retry=None):
        self._pred = predictor
        # extra_ready: () -> (checks_dict, detail_dict), merged into
        # readyz — a ModelRegistry adds per-model degradation detail.
        # oom_retry: (DeviceMemoryError) -> bool; True = the handler
        # freed device memory (registry LRU eviction) and the failed
        # dispatch may run ONCE more instead of failing its futures —
        # an OOM becomes a policy decision, not a request error
        self._extra_ready = extra_ready
        self._oom_retry = oom_retry
        self.max_queue = int(getenv("MXNET_SERVE_MAX_QUEUE", 64)) \
            if max_queue is None else int(max_queue)
        if self.max_queue < 1:
            raise MXNetError("max_queue must be >= 1")
        policy = shed_policy or os.environ.get(
            "MXNET_SERVE_SHED_POLICY", "").strip() or "deadline"
        if policy not in SHED_POLICIES:
            raise MXNetError(f"shed_policy must be one of {SHED_POLICIES}, "
                             f"got {policy!r}")
        self.shed_policy = policy
        if max_wait_ms is None:
            max_wait_ms = getenv("MXNET_SERVE_MAX_WAIT_MS", 2.0)
        self._max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        # same default chain as MicroBatcher: ctor arg >
        # MXNET_SERVE_MAX_BATCH > largest bucket
        if max_batch is None:
            max_batch = getenv("MXNET_SERVE_MAX_BATCH",
                               int(predictor.spec.max_batch))
        self._max_batch = int(max_batch)
        self.unready_latency_ms = unready_latency_ms
        self.unready_failure_rate = float(unready_failure_rate)
        self.stall_timeout_s = float(stall_timeout_s)
        self.reload_staleness_s = reload_staleness_s
        self.max_tenants = int(max_tenants)
        if self.max_tenants < 1:
            raise MXNetError("max_tenants must be >= 1")

        # lock order (sanitizer-pinned): cv -> metrics.mut (label incs
        # under admission); ready_lock never nests inside cv
        self._cv = _san.make_condition("serving.resilience.cv")
        self._tenants: Dict[str, _Tenant] = {}
        self._rr: List[str] = []      # tenant round-robin order
        self._rr_idx = 0
        self._seq = itertools.count()
        # admitted-but-unresolved requests (queued, being grouped in
        # the hold-open window, or in flight) — the registry's
        # is-this-model-idle signal.  Maintained by a done-callback on
        # every admitted future so served/expired/failed/closed all
        # decrement, and nothing is invisible mid-grouping the way a
        # queue+inflight snapshot would be
        self._live = 0
        self._closed = False
        self._fatal: Optional[BaseException] = None
        self._inflight: Optional[List[_Request]] = None

        # service-time model + watchdog state
        self._ewma_s = 0.0            # per-dispatch latency EWMA
        self._ewma_alpha = 0.3
        self._recent = deque(maxlen=50)   # dispatch outcomes (bool ok)
        self._last_dispatch_done: Optional[float] = None
        self._t_start = time.perf_counter()
        self._expired_dispatches = 0  # must stay 0 — the chaos invariant
        self._ready = False
        # serializes the read-compare-write on _ready between the
        # watchdog thread and readyz() callers: without it a flip could
        # double-count SERVE_READY_TRANSITIONS (the flapping signal)
        # and publish torn _ready/_last_checks state
        self._ready_lock = _san.make_lock("serving.resilience.ready")
        self._last_checks: Dict[str, bool] = {}
        self._last_detail: dict = {}
        self._ready_reasons: List[str] = ["no_evaluation_yet"]
        if _metrics.ENABLED:
            _metrics.SERVE_READY.set(0.0)

        self._thread = threading.Thread(
            target=self._loop, name="mxt-serve-resilient", daemon=True)
        self._thread.start()
        self._watch_stop = threading.Event()
        self._watch_interval = max(0.01, float(watchdog_interval_s))
        self._watchdog = threading.Thread(
            target=self._watch, name="mxt-serve-watchdog", daemon=True)
        self._watchdog.start()

    # -- client side ---------------------------------------------------------
    def submit(self, tenant: str = "default",
               deadline_ms: Optional[float] = None, priority: int = 0,
               max_new_tokens: Optional[int] = None,
               **inputs) -> Future:
        """Enqueue one request for ``tenant``.

        Raises ``Overloaded`` synchronously when admission control
        rejects (queue full, or — under the ``deadline`` policy — the
        estimated wait already exceeds ``deadline_ms``); a malformed
        request fails its own returned future (MicroBatcher contract).
        An admitted request resolves to its output rows, or to
        ``DeadlineExceeded`` if its deadline passes before dispatch."""
        if max_new_tokens is not None:
            # same loud refusal as MicroBatcher.submit: a generation
            # here would hold a coalesced group hostage for its whole
            # output length — route it to continuous batching
            from .batcher import GenerativeRouteError
            raise GenerativeRouteError(
                f"max_new_tokens={max_new_tokens}: generative decode "
                f"must not ride the request-coalescing tier — use "
                f"serving.decode.DecodeEngine (per-step join/leave, "
                f"EDF at decode-step granularity) or "
                f"BucketingModule.generate")
        try:
            self._pred._check_names(inputs)
            host = {n: self._pred._as_host(n, v)
                    for n, v in inputs.items()}
            self._pred._check_request(host)
        except Exception as e:  # noqa: BLE001 — delivered to caller
            f = Future()
            f.set_exception(e)
            return f
        now = time.perf_counter()
        deadline = None if deadline_ms is None \
            else now + float(deadline_ms) / 1e3
        req = _Request(host, tenant, priority, deadline)
        # the admission phase records for SHED requests too (the span
        # closes on the Overloaded raise) — a timeline shows both what
        # was admitted and what bounced, under the same trace id scheme
        with _flight.phase_span("serve_admission", cat="serving",
                                trace_id=req.trace_id), self._cv:
            if self._closed:
                raise BatcherClosedError("ResilientServer is closed")
            if self._fatal is not None:
                raise BatcherDeadError(
                    f"ResilientServer worker died: {self._fatal}")
            t = self._tenant(tenant)
            if len(t.heap) >= self.max_queue:
                retry = self._estimate_wait_s(self._total_rows())
                self._shed(t, "queue_full")
                raise Overloaded(
                    f"tenant '{tenant}' queue full "
                    f"({self.max_queue} requests); retry after "
                    f"~{retry:.3f}s", retry_after_s=retry)
            if self.shed_policy == "deadline" and deadline is not None:
                # estimated wait until DISPATCH START — rows AHEAD only,
                # matching the expiry rule (a request that starts
                # dispatching before its deadline is served).  Counting
                # the request's own dispatch here would make a one-off
                # slow dispatch self-sustaining: the inflated EWMA sheds
                # every deadlined request even at an empty queue, so
                # nothing dispatches and the EWMA never recovers
                est = self._estimate_wait_s(self._total_rows())
                if now + est > deadline:
                    self._shed(t, "deadline_unmeetable")
                    raise Overloaded(
                        f"tenant '{tenant}': estimated wait "
                        f"{est * 1e3:.1f}ms exceeds deadline "
                        f"{float(deadline_ms):.1f}ms; retry after "
                        f"~{est:.3f}s", retry_after_s=est)
            req.tref = t
            heapq.heappush(t.heap, (-req.priority,
                                    deadline if deadline is not None
                                    else float("inf"),
                                    next(self._seq), req))
            t.rows_queued += req.rows
            t.admitted += 1
            self._live += 1
            req.future.add_done_callback(self._one_resolved)
            if _metrics.ENABLED:
                _metrics.SERVE_ADMITTED.inc(tenant=tenant)
                _metrics.SERVE_QUEUE_DEPTH.set(self._total_requests())
            self._cv.notify_all()
        return req.future

    def predict(self, tenant: str = "default",
                deadline_ms: Optional[float] = None, priority: int = 0,
                **inputs) -> List[_np.ndarray]:
        """Blocking submit — raises ``Overloaded`` / ``DeadlineExceeded``
        / the dispatch error in the caller's thread."""
        return self.submit(tenant=tenant, deadline_ms=deadline_ms,
                           priority=priority, **inputs).result()

    def warmup(self, keys=None, execute: bool = True) -> "ResilientServer":
        """AOT-compile the predictor's buckets, pre-execute each once,
        and refresh readiness — the replica flips ready here, before
        taking traffic.

        The execution touch matters: an AOT-compiled executable's FIRST
        invocation pays a one-time lazy-linking cost (100ms-class on
        some backends) that would otherwise land on the first unlucky
        request per bucket — inflating its latency, poisoning the
        dispatch EWMA the shed policy trusts, and tripping the readyz
        latency check at cold start.  ``execute=False`` restores
        compile-only warmup."""
        self._pred.warmup(keys)
        if execute:
            for key in (keys if keys is not None
                        else self._pred.spec.all_keys()):
                shapes = self._pred.spec.bucket_input_shapes(tuple(key))
                self._pred._predict_routed(
                    {n: _np.zeros(s, self._pred._input_dtypes[n])
                     for n, s in shapes.items()})
        self._update_ready()
        return self

    def close(self, timeout: float = 5.0) -> None:
        """Stop the scheduler + watchdog; fail everything still queued
        with a typed error instead of hanging callers."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
        self._watch_stop.set()
        self._watchdog.join(timeout=1.0)
        leftovers = []
        with self._cv:
            for t in self._tenants.values():
                while t.heap:
                    leftovers.append(heapq.heappop(t.heap)[-1])
                t.rows_queued = 0
        err = BatcherClosedError("ResilientServer closed before dispatch")
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(err)
        # final readiness evaluation: a closed server must not keep
        # advertising ready=1 through the registry (the watchdog that
        # would have noticed is stopped now)
        self._update_ready()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- admission internals -------------------------------------------------
    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            if len(self._tenants) >= self.max_tenants:
                self._evict_idle_tenant()
            t = self._tenants[name] = _Tenant(name)
            self._rr.append(name)
        return t

    def _evict_idle_tenant(self) -> None:
        """Drop one tenant with an empty queue to cap tenant-table
        growth (high-cardinality ``tenant=`` values would otherwise
        accumulate state forever).  All-busy means genuine overload:
        reject the new tenant with backpressure.  Caller holds _cv."""
        for name, t in self._tenants.items():
            if not t.heap:
                del self._tenants[name]
                self._rr.remove(name)
                if _metrics.ENABLED:
                    # per-tenant metric series must not outlive the
                    # eviction that exists to bound tenant cardinality:
                    # counters fold into tenant="_evicted" (totals
                    # preserved), the point-in-time goodput gauge drops
                    for c in (_metrics.SERVE_ADMITTED,
                              _metrics.SERVE_SHED,
                              _metrics.SERVE_EXPIRED):
                        c.fold_label("tenant", name, "_evicted")
                    _metrics.SERVE_GOODPUT.remove(tenant=name)
                return
        retry = self._estimate_wait_s(self._total_rows())
        if _metrics.ENABLED:
            _metrics.SERVE_SHED.inc(reason="tenant_table_full")
        raise Overloaded(
            f"tenant table full ({self.max_tenants} tenants, all with "
            f"queued work); retry after ~{retry:.3f}s",
            retry_after_s=retry)

    def _total_rows(self) -> int:
        return sum(t.rows_queued for t in self._tenants.values())

    def _total_requests(self) -> int:
        return sum(len(t.heap) for t in self._tenants.values())

    def _has_work(self) -> bool:
        return any(t.heap for t in self._tenants.values())

    def _estimate_wait_s(self, rows_ahead: int) -> float:
        """Expected time until ``rows_ahead`` queued rows have cleared
        (i.e. until a newly admitted request would start dispatching):
        dispatches needed x the dispatch-latency EWMA.  Zero until the
        first dispatch lands — a cold server admits everything and lets
        the queue bound do the work."""
        if self._ewma_s <= 0.0 or rows_ahead <= 0:
            return 0.0
        return math.ceil(rows_ahead / self._max_batch) * self._ewma_s

    def _shed(self, t: _Tenant, reason: str) -> None:
        t.shed += 1
        if _metrics.ENABLED:
            _metrics.SERVE_SHED.inc(tenant=t.name, reason=reason)
        if _goodput.ENABLED:
            # a refused admission wasted no measurable wall-clock yet —
            # count the event so report() shows the shed pressure
            _goodput.attribute("shed", 0.0)

    # -- scheduler -----------------------------------------------------------
    def _pop_into(self, group: List[_Request], expired: List[_Request],
                  cap: int) -> int:
        """Pop runnable requests round-robin across tenants (one per
        tenant per turn — fairness), highest priority / earliest
        deadline first within a tenant.  Expired heads are drained into
        ``expired`` without counting toward the row cap.  Caller holds
        the cv lock."""
        rows = sum(r.rows for r in group)
        names = self._rr
        if not names:
            return rows
        n = len(names)
        idle = 0
        while idle < n and rows < cap:
            t = self._tenants[names[self._rr_idx % n]]
            self._rr_idx += 1
            popped = False
            while t.heap:
                req = t.heap[0][-1]
                now = time.perf_counter()
                if req.deadline is not None and now >= req.deadline:
                    heapq.heappop(t.heap)
                    t.rows_queued -= req.rows
                    expired.append(req)
                    continue  # keep draining expired heads
                if group and rows + req.rows > cap:
                    break  # leave for the next group
                heapq.heappop(t.heap)
                t.rows_queued -= req.rows
                group.append(req)
                rows += req.rows
                popped = True
                break  # one pop per tenant per turn
            idle = 0 if popped else idle + 1
        return rows

    def _take_group(self):
        """Block until work or shutdown.  Returns (group, expired) or
        None when closed with nothing left."""
        expired: List[_Request] = []
        with self._cv:
            while True:
                if self._closed and not self._has_work():
                    return None
                group: List[_Request] = []
                rows = self._pop_into(group, expired, self._max_batch)
                if group:
                    # hold the batch open briefly for more arrivals
                    hold_until = time.perf_counter() + self._max_wait_s
                    while rows < self._max_batch and not self._closed:
                        remaining = hold_until - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                        rows = self._pop_into(group, expired,
                                              self._max_batch)
                    if _metrics.ENABLED:
                        _metrics.SERVE_QUEUE_DEPTH.set(
                            self._total_requests())
                    return group, expired
                if expired:
                    return group, expired  # deliver expirations promptly
                # reached only when every tenant heap is empty (a
                # non-empty heap always yields a group or an expired
                # entry above), so nothing can expire while we sleep
                # and submit()/close() notify under this lock — an
                # untimed wait costs zero idle wakeups
                self._cv.wait()

    def _expire(self, reqs: List[_Request]) -> None:
        for r in reqs:
            t = r.tref
            t.expired += 1
            if _metrics.ENABLED:
                _metrics.SERVE_EXPIRED.inc(tenant=r.tenant)
            self._publish_goodput(t)
            if not r.future.done():
                waited = (time.perf_counter() - r.t0) * 1e3
                if _goodput.ENABLED:
                    # an expired request's whole queue wait was wasted
                    _goodput.attribute("shed", waited / 1e3)
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed after {waited:.1f}ms in queue "
                    f"(tenant '{r.tenant}'); request was dropped before "
                    f"dispatch"))

    def _publish_goodput(self, t: _Tenant) -> None:
        if not _metrics.ENABLED or not t.admitted:
            return
        # membership check AND set under _cv: eviction (which holds
        # _cv) removes the gauge child, so an unlocked check-then-set
        # here could resurrect it right after removal and defeat the
        # cardinality bound.  Never called with _cv held (_expire and
        # _dispatch_group both run outside the lock).
        with self._cv:
            if self._tenants.get(t.name) is t:
                _metrics.SERVE_GOODPUT.set(t.served / t.admitted,
                                           tenant=t.name)

    def _run_dispatch(self, stacked):
        """One predictor dispatch, with the registry's OOM second
        chance: a typed ``DeviceMemoryError`` (real RESOURCE_EXHAUSTED
        or the ``memory.oom`` chaos site) consults ``oom_retry`` —
        when the handler evicts enough colder models/buckets to free
        HBM, the dispatch runs once more instead of failing its
        callers.  A second OOM (or no handler) propagates."""
        try:
            return self._pred._predict_routed(stacked)
        except _memory.DeviceMemoryError as e:
            handler = self._oom_retry
            if handler is None or not handler(e):
                raise
            # str(e), never the exception object: a buffering log
            # handler would pin e.__traceback__ and with it the
            # dispatch frame's device buffers
            log.warning("dispatch OOM handled by budget arbiter — "
                        "retrying once: %s", str(e))
            return self._pred._predict_routed(stacked)

    @hot_path
    def _dispatch_group(self, group: List[_Request]) -> None:
        t0 = time.perf_counter()
        # the authoritative expired-work gate, evaluated at dispatch
        # start: _pop_into already filtered, but the hold-open window
        # ran after that — a request that expired IN the window is
        # expired here (typed), never padded or dispatched
        dead = [r for r in group
                if r.deadline is not None and t0 >= r.deadline]
        if dead:
            self._expire(dead)
            group = [r for r in group if r not in dead]
            if not group:
                return
        fl = _flight.ENABLED
        if fl:
            record_group_queue_wait(group, t0 * 1e6)
        scope = group_trace_scope(group) if fl else _nullcontext()
        ok = True
        try:
            with scope:
                with _flight.phase_span("serve_stack", cat="serving"):
                    stacked = stack_requests(self._pred.spec, group)
                # independent tripwire reading for the chaos invariant
                # (pinned at 0 by the tests): dispatch truly starts HERE
                # — a fresh clock read, not the gate's t0, so a future
                # reordering or weakening of the gate above still shows
                # up as a nonzero expired-dispatch count
                t_start = time.perf_counter()
                for r in group:
                    if r.deadline is not None and t_start >= r.deadline:
                        self._expired_dispatches += 1
                outs = self._run_dispatch(stacked)
            lo = 0
            for r in group:
                if not r.future.done():
                    r.future.set_result([o[lo:lo + r.rows] for o in outs])
                lo += r.rows
            now = time.perf_counter()
            for r in group:
                t = r.tref
                t.served += 1
                self._publish_goodput(t)
                if _metrics.ENABLED:
                    _metrics.SERVE_LATENCY_SECONDS.observe(
                        now - r.t0, exemplar=r.trace_id)
                if _goodput.ENABLED:
                    # feed the SLO p99 sliding window (docs/goodput.md)
                    _goodput.serve_latency_sample((now - r.t0) * 1e3)
                if fl:
                    # slow-request watchdog: end-to-end latency vs EWMA
                    _flight.note("serve_request", now - r.t0)
            if _metrics.ENABLED:
                _metrics.SERVE_REQUESTS.inc(len(group))
                _metrics.SERVE_COALESCED_ROWS.set(
                    sum(r.rows for r in group))
        except Exception as e:  # noqa: BLE001 — failures go to callers
            ok = False
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
        finally:
            dt = time.perf_counter() - t0
            self._ewma_s = dt if self._ewma_s == 0.0 else \
                self._ewma_alpha * dt + (1 - self._ewma_alpha) * self._ewma_s
            self._last_dispatch_done = time.perf_counter()
            self._recent.append(ok)

    def _loop(self) -> None:
        try:
            while True:
                res = self._take_group()
                if res is None:
                    return
                group, expired = res
                self._expire(expired)
                if group:
                    # _dispatch_group re-checks deadlines at dispatch
                    # start (requests can expire during the hold-open
                    # window); tracked so _die can fail these futures
                    # too if the dispatch dies with a non-Exception
                    # (worker death) — cleared only on normal return, a
                    # finally would wipe it before _die could read it
                    self._inflight = group
                    self._dispatch_group(group)
                    self._inflight = None
        except BaseException as e:  # noqa: BLE001 — worker death
            # cleanup then exit quietly: _die records the cause (submit
            # raises it), fails every queued future typed, and logs
            self._die(e)

    def _die(self, exc: BaseException) -> None:
        err = BatcherDeadError(
            f"ResilientServer worker died: {type(exc).__name__}: {exc}")
        log.error("%s", err)
        leftovers = list(self._inflight or [])
        self._inflight = None
        with self._cv:
            self._fatal = exc
            for t in self._tenants.values():
                while t.heap:
                    leftovers.append(heapq.heappop(t.heap)[-1])
                t.rows_queued = 0
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(err)

    # -- health / readiness --------------------------------------------------
    def healthz(self) -> dict:
        """Liveness: is the process worth keeping?  (Restart on False —
        the readiness question 'should I get traffic?' is readyz().)"""
        alive = self._thread.is_alive() and self._fatal is None
        return {
            "ok": bool(alive and not self._closed),
            "scheduler_alive": self._thread.is_alive(),
            "watchdog_alive": self._watchdog.is_alive(),
            "closed": self._closed,
            "fatal": None if self._fatal is None else repr(self._fatal),
            "uptime_s": time.perf_counter() - self._t_start,
        }

    def _compute_ready(self) -> Tuple[bool, Dict[str, bool], dict]:
        checks: Dict[str, bool] = {}
        detail: dict = {}
        # 1. warmup: every bucket compiled at least ONCE — a cold
        # replica would pay full hot-path compiles on its first
        # requests.  Counted over ever-compiled keys, not currently
        # resident ones: under a multi-model HBM budget, buckets the
        # registry evicted rebuild via the persistent compile cache
        # (bounded, disk-hit cost), and churn must not take an
        # otherwise-healthy replica out of rotation forever
        want = len(self._pred.spec.all_keys())
        have = self._pred.num_compiled
        ever = len(getattr(self._pred, "_ever_compiled", ()) or ())
        checks["warmup_complete"] = max(have, ever) >= want
        detail["compiled_buckets"] = f"{have}/{want}"
        # 2. persistent compile cache: configured implies wired
        from .. import base as _base
        checks["compile_cache"] = (
            not os.environ.get("MXNET_COMPILE_CACHE_DIR")
            or _base._COMPILE_CACHE_WIRED)
        # 2b. HBM: the compiled per-bucket cost table (always detail)
        # plus the soft-budget check when MXNET_HBM_BUDGET_MB is set —
        # a replica whose tracked device bytes blew the budget must
        # leave rotation BEFORE the hardware OOMs it mid-request
        try:
            ms = self._pred.memory_stats()
            detail["bucket_hbm_peak_bytes"] = ms["peak_bytes_max"]
            detail["serve_weights_bytes"] = ms["weights_bytes"]
        except Exception:  # noqa: BLE001 — stats are best-effort
            pass
        if _memory.ENABLED and _memory.BUDGET_MB > 0:
            tracked = _memory.tracked_bytes()
            detail["hbm_tracked_bytes"] = int(tracked)
            checks["hbm_budget"] = \
                tracked <= _memory.BUDGET_MB * 1024 * 1024
        # 2c. perf-regression sentinel (ISSUE 13): once a persisted
        # baseline is armed, an active step-time/dispatch regression
        # takes the replica out of rotation — a "healthy" process
        # running 2x slower than its own recorded baseline is not
        # traffic-worthy.  Guarded: readiness must never fail because
        # of the introspector.
        try:
            from ..observability import introspect as _int
            if _int.ENABLED and _int.sentinel_armed():
                active = _int.regression_active()
                checks["perf_regression"] = not active
                if active:
                    detail["perf_sentinel"] = {
                        p: {"kind": s["kind"],
                            "baseline_p50_ms":
                                (s["baseline"] or {}).get(
                                    "step_time_p50_ms"),
                            "current_p50_ms":
                                (s["current"] or {}).get(
                                    "step_time_p50_ms")}
                        for p, s in _int.sentinel_state()["phases"].items()
                        if s["active"]}
        except Exception:  # noqa: BLE001 — sentinel is best-effort here
            pass
        # 2d. SLO burn (ISSUE 16): a declared goodput / serve-p99
        # target currently burning takes the replica out of rotation —
        # the monitor already warned, counted mxnet_slo_burn_total and
        # journaled; readyz is where the balancer finds out.  Guarded:
        # readiness must never fail because of the ledger.
        try:
            if _goodput.ENABLED and _goodput.slo_armed():
                checks["slo_burn"] = not _goodput.slo_burning()
                detail["slo"] = _goodput.slo_state()
        except Exception:  # noqa: BLE001 — monitor is best-effort here
            pass
        # 3. dispatch latency EWMA vs threshold
        lat_ms = self._ewma_s * 1e3
        detail["dispatch_ewma_ms"] = round(lat_ms, 3)
        checks["dispatch_latency"] = (
            not self.unready_latency_ms
            or lat_ms <= float(self.unready_latency_ms))
        # 4. failure rate over the recent-dispatch window
        recent = list(self._recent)
        rate = (len(recent) - sum(recent)) / len(recent) if recent else 0.0
        detail["failure_rate"] = round(rate, 3)
        checks["failure_rate"] = rate <= self.unready_failure_rate
        # 5. dispatch stall: queued work but nothing completing
        now = time.perf_counter()
        last = self._last_dispatch_done
        detail["last_dispatch_age_s"] = None if last is None \
            else round(now - last, 3)
        with self._cv:
            has_work = self._has_work()
        anchor = last if last is not None else self._t_start
        checks["dispatch_stall"] = not (
            has_work and now - anchor > self.stall_timeout_s)
        # 6. hot-reload freshness (only when auto-reload is running)
        reload_thread = getattr(self._pred, "_reload_thread", None)
        if reload_thread is not None:
            staleness = self.reload_staleness_s
            if staleness is None:
                staleness = 3.0 * getattr(self._pred,
                                          "_reload_interval_s", 30.0)
            age = time.monotonic() - getattr(
                self._pred, "_last_reload_ok", time.monotonic())
            detail["reload_age_s"] = round(age, 3)
            checks["hot_reload_fresh"] = age <= staleness
        # 7. the scheduler itself
        checks["scheduler_alive"] = (self._thread.is_alive()
                                     and self._fatal is None)
        # 8. caller-supplied checks/detail (the ModelRegistry's
        # per-model degradation + budget view).  Guarded: readiness
        # must never fail because of the hook itself
        if self._extra_ready is not None:
            try:
                ec, ed = self._extra_ready()
                checks.update(ec or {})
                detail.update(ed or {})
            except Exception:  # noqa: BLE001 — hook is best-effort
                pass
        ready = all(checks.values()) and not self._closed
        return ready, checks, detail

    def _update_ready(self) -> None:
        ready, checks, detail = self._compute_ready()
        with self._ready_lock:
            if ready != self._ready:
                log.warning("serving readiness %s -> %s (%s)",
                            self._ready, ready,
                            [k for k, v in checks.items() if not v]
                            or "ok")
                if _metrics.ENABLED:
                    _metrics.SERVE_READY_TRANSITIONS.inc(
                        direction="up" if ready else "down")
            self._ready = ready
            self._last_checks = checks
            self._ready_reasons = [k for k, v in checks.items() if not v]
            self._last_detail = detail
            if _metrics.ENABLED:
                _metrics.SERVE_READY.set(1.0 if ready else 0.0)

    def readyz(self) -> dict:
        """Traffic-worthiness: the load balancer's question.  Evaluates
        fresh (the watchdog also refreshes every interval so the gauge
        and transition counter move without anyone polling)."""
        self._update_ready()
        return {"ready": self._ready,
                "reasons": list(self._ready_reasons),
                "checks": dict(self._last_checks),
                "detail": dict(self._last_detail)}

    def _watch(self) -> None:
        while not self._watch_stop.wait(self._watch_interval):
            try:
                self._update_ready()
            except Exception as e:  # noqa: BLE001 — watchdog never dies
                log.warning("readiness watchdog evaluation failed: %s", e)

    def _one_resolved(self, _future) -> None:
        # future resolutions happen outside the cv lock everywhere
        # (_expire/_die/close/dispatch), so taking it here cannot
        # self-deadlock
        with self._cv:
            self._live = max(0, self._live - 1)

    def pending(self) -> int:
        """Admitted requests not yet resolved (queued, being grouped,
        or in flight) — the registry's is-this-model-idle question (a
        model with pending work is never a weights-eviction victim:
        evicting it would fail or thrash the very requests it still
        owes)."""
        with self._cv:
            return self._live

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        """Point-in-time serving stats (the per-server complement of
        ``observability.snapshot()["serving"]``)."""
        with self._cv:
            tenants = {
                t.name: {"admitted": t.admitted, "served": t.served,
                         "expired": t.expired, "shed": t.shed,
                         "queued": len(t.heap),
                         "goodput": (t.served / t.admitted)
                         if t.admitted else 1.0}
                for t in self._tenants.values()}
            depth = self._total_requests()
            rows = self._total_rows()
        return {"tenants": tenants, "queue_depth": depth,
                "rows_queued": rows,
                "dispatch_ewma_ms": round(self._ewma_s * 1e3, 3),
                "expired_dispatches": self._expired_dispatches,
                "ready": self._ready, "max_queue": self.max_queue,
                "shed_policy": self.shed_policy}
