"""Continuous batching for generative decode: paged KV serving with
per-step join/leave.

The coalescing tier (``MicroBatcher`` / ``ResilientServer``) batches at
*request* granularity — correct for classifiers, a throughput cliff for
generation: one long sequence pins its whole coalesced group for its
full output length (the ``rnn/`` + ``BucketingModule`` hostage path the
roadmap names).  ``DecodeEngine`` is the jax-native answer, the MXNet
bucketing-executor story (arxiv 1512.01274) crossed with TF's
dataflow-level dynamic batching (arxiv 1605.08695):

  * **ONE donated XLA dispatch per decode step** over the whole
    in-flight slot set.  Sequences join and leave *between* steps —
    a join is three host-array writes (token, position, slot), never a
    new program, so churn cannot change the dispatch count
    (``make decode-smoke`` pins dispatches == steps).
  * **paged KV on a pow2 bucket lattice** — decode state leaves carry a
    slot axis and a capacity axis sized ``pages x
    MXNET_DECODE_PAGE_TOKENS``; the (slots, pages) key routes through a
    stock ``buckets.BucketSpec`` (``buckets.page_lattice``), so mixed
    length sequences share ONE precompiled lattice and growth across a
    page boundary re-routes to the neighbouring precompiled key —
    ``SERVE_COMPILES`` stays flat under traffic, the serving tier's
    standing contract.
  * **KV pages are a first-class, evictable HBM resource** — the whole
    decode state registers in the PR 9 ledger under a dedicated
    ``serve_kv_pages`` tag; growth asks ``memory.ensure_headroom``
    FIRST (the PR 14 ask-first discipline), and under pressure the
    registry's LRU arbiter reclaims cold sequences' pages *before* any
    model weights (``ModelRegistry._make_room`` phase 0) — an evicted
    sequence fails with a typed ``SequenceEvicted`` carrying
    ``retry_after_s``, never a silent hang.
  * **EDF shedding at decode-step granularity** — admission sheds a
    sequence whose deadline the remaining-tokens x step-EWMA estimate
    (``resilience.StepEDF``) already cannot meet; between steps the
    engine expires passed deadlines and, when admitted work is waiting,
    preempts actives whose deadlines became unmeetable — typed
    ``DeadlineExceeded``, the slot goes to the earliest-deadline
    waiter.
  * **house invariants** — the step's donation is declared via
    ``note_program`` contracts and verified by
    ``analysis.audit_programs()``; every observability hook is one
    boolean test when its subsystem is off; failures at the
    ``serving.decode_step`` chaos site degrade typed with sequence
    state consistent across a retry.

``ToyLM`` (self-contained) and ``CellModel`` (any steppable
``rnn.BaseRNNCell`` via its one-step Symbol -> ``GraphPlan``) plug into
the engine's model protocol; ``BucketingModule.generate`` routes here.
docs/decode_serving.md is the guide.
"""
from __future__ import annotations

import argparse
import json
import logging
import sys
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as _np

from ..analysis import hot_path, sanitizer as _san
from ..base import MXNetError, getenv
from ..faultinject import fire as _fi_fire
from ..observability import flight as _flight
from ..observability import goodput as _goodput
from ..observability import introspect as _introspect
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from .batcher import GenerativeRouteError
from .buckets import bucket_label, page_lattice
from .resilience import DeadlineExceeded, Overloaded, StepEDF

log = logging.getLogger(__name__)

__all__ = ["DecodeEngine", "ToyLM", "CellModel", "SequenceEvicted",
           "GenerativeRouteError", "reclaim_kv_pages", "live_engines"]

#: ledger tag for paged decode state — alongside serve_weights /
#: serve_host_params in the multi-model cost model, and the CHEAPEST
#: victim tier (a shed sequence retries; weights must re-upload)
KV_TAG = "serve_kv_pages"


class SequenceEvicted(Overloaded):
    """This sequence's KV pages were reclaimed under HBM pressure (the
    budget arbiter preferred them over model weights).  Typed
    reject-with-backpressure: ``retry_after_s`` estimates when decode
    capacity frees — resubmit the prompt; nothing was silently lost
    because nothing was silently kept."""


class DecodeClosedError(MXNetError):
    """The engine was closed before this sequence finished (or before
    it could be submitted)."""


class _Seq:
    __slots__ = ("sid", "prompt", "max_new", "deadline", "priority",
                 "tenant", "future", "generated", "pos", "slot", "t0",
                 "trace_id", "eos")

    def __init__(self, sid: int, prompt, max_new: int,
                 deadline: Optional[float], priority: int, tenant: str,
                 eos: Optional[int]):
        self.sid = sid
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.deadline = deadline  # absolute perf_counter time, or None
        self.priority = int(priority)
        self.tenant = str(tenant)
        self.future: Future = Future()
        self.generated: List[int] = []
        self.pos = 0          # next position to be written (tokens consumed)
        self.slot: Optional[int] = None
        self.t0 = time.perf_counter()
        self.trace_id = _flight.new_trace_id() if _flight.ENABLED else None
        self.eos = eos

    def remaining(self) -> int:
        """Decode steps left: unconsumed prompt + ungenerated tokens."""
        return max(0, len(self.prompt) - 1 - self.pos) \
            + max(0, self.max_new - len(self.generated))


class _PageTable:
    """Ledger-visible holder for one engine's paged decode state.  The
    state leaves themselves rotate every donated step; this stable
    object carries their byte total so the ``serve_kv_pages`` tag has
    one long-lived registrant per engine (weakref death on engine
    close returns the bytes — the leak gate pins it)."""
    __slots__ = ("__weakref__",)


# live engines, for the registry's phase-0 KV reclaim (and operators)
_engines_lock = _san.make_lock("serving.decode.engines")
_ENGINES: "weakref.WeakSet" = weakref.WeakSet()


def live_engines() -> list:
    with _engines_lock:
        return list(_ENGINES)


def reclaim_kv_pages(deficit: float, why: str = "") -> float:
    """Process-wide KV-page reclaim: ask every live engine to shed its
    coldest sequences' pages until ~``deficit`` ledger bytes freed.
    ``ModelRegistry._make_room`` runs this as phase 0 — KV pages are
    cheaper victims than bucket executables or model weights.  Returns
    bytes freed (measured from the ledger, not trusted estimates)."""
    freed = 0.0
    for eng in live_engines():
        if freed >= deficit:
            break
        try:
            freed += eng.release_kv_pages(deficit - freed, why=why)
        except Exception as e:  # noqa: BLE001 — reclaim is best-effort
            log.warning("decode KV reclaim on %r failed (%s): %s",
                        getattr(eng, "name", "?"), why, str(e))
    return freed


# ---------------------------------------------------------------------------
# models
# ---------------------------------------------------------------------------
class ToyLM:
    """Self-contained decode model for tests/bench/smoke: embedding ->
    tanh recurrence -> sliding-window attention over the paged KV log
    -> vocab projection, greedy argmax.

    Two properties the engine's correctness gates lean on:

      * **slot independence** — row ``i`` of every op reads only row
        ``i`` of state/tokens (matmuls are row-wise) — so continuous
        batching is bitwise equal to a solo run in the same slot
        bucket, join/leave churn included;
      * **capacity independence** — the KV read is a fixed ``window``
        of positions ``<= pos`` (clamped gather, invalid lanes masked
        to exact zeros), so routing to a larger pages bucket changes
        where the log is STORED, never the values read — growth across
        page boundaries is bitwise-stable too.
    """

    def __init__(self, vocab: int = 32, dim: int = 16, window: int = 8):
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.window = int(window)
        if min(self.vocab, self.dim, self.window) < 1:
            raise MXNetError("ToyLM needs vocab/dim/window >= 1")

    #: state leaves with a pages-backed capacity axis (axis index)
    state_capacity_axes = {"kv": 1}

    def init_params(self, seed: int = 0) -> Dict[str, _np.ndarray]:
        rng = _np.random.RandomState(seed)
        s = 0.2
        return {
            "emb": rng.uniform(-s, s, (self.vocab, self.dim))
            .astype(_np.float32),
            "wx": rng.uniform(-s, s, (self.dim, self.dim))
            .astype(_np.float32),
            "wh": rng.uniform(-s, s, (self.dim, self.dim))
            .astype(_np.float32),
            "out": rng.uniform(-s, s, (self.dim, self.vocab))
            .astype(_np.float32),
        }

    def state_shapes(self, slots: int, capacity: int) -> Dict[str, tuple]:
        return {"h": ((slots, self.dim), _np.float32),
                "kv": ((slots, capacity, self.dim), _np.float32)}

    def step(self, params, state, tokens, pos):
        import jax.numpy as jnp
        x = params["emb"][tokens]                              # (S, D)
        h = jnp.tanh(x @ params["wx"] + state["h"] @ params["wh"])
        kv = state["kv"]
        cap = kv.shape[1]
        write = (jnp.arange(cap)[None, :] == pos[:, None])     # (S, C)
        kv = jnp.where(write[..., None], h[:, None, :], kv)
        # fixed-width window over positions [pos-window+1, pos]:
        # clamped gather + exact-zero masking keeps the read identical
        # across capacity buckets (see class docstring)
        offs = jnp.arange(self.window)                         # (W,)
        idx = pos[:, None] - offs[None, :]                     # (S, W)
        valid = (idx >= 0).astype(kv.dtype)
        got = jnp.take_along_axis(
            kv, jnp.clip(idx, 0, cap - 1)[..., None], axis=1)  # (S, W, D)
        r = (got * valid[..., None]).sum(axis=1) \
            / valid.sum(axis=1, keepdims=True)
        logits = (h + r) @ params["out"]                       # (S, V)
        return logits, {"h": h, "kv": kv}


class CellModel:
    """Adapt a *steppable* ``rnn.BaseRNNCell`` into the engine's model
    protocol: the cell's one-step Symbol (``cell(x, states)``) becomes
    a ``GraphPlan`` executed inside the donated decode step (the same
    jax-traceable plan the serving predictor compiles), wrapped with a
    token embedding, a paged KV log of the cell outputs, and a vocab
    projection.  This is how ``rnn/`` + ``BucketingModule`` generation
    routes through continuous batching instead of holding a coalesced
    micro-batch hostage.

    Non-steppable cells (``FusedRNNCell``, ``BidirectionalCell``) are
    rejected with a typed ``GenerativeRouteError`` — ``unfuse()`` a
    fused stack first."""

    def __init__(self, cell, vocab: int, seed: int = 0):
        if not getattr(cell, "steppable", False):
            raise GenerativeRouteError(
                f"{type(cell).__name__} cannot emit a one-token decode "
                f"step (fused/bidirectional cells consume whole "
                f"sequences) — unfuse() it, or build the engine on a "
                f"steppable cell (serving.decode.CellModel, "
                f"docs/decode_serving.md)")
        from .. import symbol as _symbol
        from ..symbol.graph import GraphPlan
        self.vocab = int(vocab)
        self._infos = list(cell.state_info)
        x = _symbol.Variable("decode_x")
        states = [_symbol.Variable(f"decode_state{i}")
                  for i in range(len(self._infos))]
        out, new_states = cell(x, states)
        self._plan = GraphPlan(_symbol.Group([out] + list(new_states)))
        self._state_names = [f"decode_state{i}"
                             for i in range(len(self._infos))]
        # one-step shape inference at batch 1 sizes every cell param
        # (and the cell's output width, which the KV log and the vocab
        # projection both ride)
        dim = self._infos[0]["shape"][-1]
        self.dim = int(dim)
        known = {"decode_x": (1, self.dim)}
        for n, info in zip(self._state_names, self._infos):
            known[n] = (1,) + tuple(info["shape"][1:])
        arg_shapes, out_shapes, _aux = self._plan.symbol.infer_shape(**known)
        self._arg_shapes = dict(zip(self._plan.symbol.list_arguments(),
                                    arg_shapes))
        self.out_dim = int(out_shapes[0][-1])
        self._seed = int(seed)

    @property
    def state_capacity_axes(self):
        return {"kv": 1}

    def init_params(self, seed: Optional[int] = None):
        rng = _np.random.RandomState(self._seed if seed is None else seed)
        s = 0.2
        params = {
            "decode_emb": rng.uniform(-s, s, (self.vocab, self.dim))
            .astype(_np.float32),
            "decode_out": rng.uniform(-s, s, (self.out_dim, self.vocab))
            .astype(_np.float32),
        }
        skip = {"decode_x"} | set(self._state_names)
        for name, shp in self._arg_shapes.items():
            if name in skip:
                continue
            if name.endswith("_bias"):
                params[name] = _np.zeros(shp, dtype=_np.float32)
            else:
                params[name] = rng.uniform(-s, s, shp).astype(_np.float32)
        return params

    def state_shapes(self, slots: int, capacity: int) -> Dict[str, tuple]:
        out = {"kv": ((slots, capacity, self.out_dim), _np.float32)}
        for n, info in zip(self._state_names, self._infos):
            out[n] = ((slots,) + tuple(info["shape"][1:]), _np.float32)
        return out

    def step(self, params, state, tokens, pos):
        import jax
        import jax.numpy as jnp
        x = params["decode_emb"][tokens]                       # (S, D)
        args = {n: v for n, v in params.items()
                if n not in ("decode_emb", "decode_out")}
        args["decode_x"] = x
        for n in self._state_names:
            args[n] = state[n]
        # fixed key: one decode step consumes no randomness in stock
        # cells; determinism across identical requests is the contract
        outs, _aux = self._plan.run(args, {}, jax.random.PRNGKey(0),
                                    is_train=False)
        cell_out, new_states = outs[0], outs[1:]
        kv = state["kv"]
        cap = kv.shape[1]
        write = (jnp.arange(cap)[None, :] == pos[:, None])
        kv = jnp.where(write[..., None], cell_out[:, None, :], kv)
        logits = cell_out @ params["decode_out"]
        new_state = {"kv": kv}
        for n, ns in zip(self._state_names, new_states):
            new_state[n] = ns
        return logits, new_state


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------
class DecodeEngine:
    """Continuous-batching decode server over one model.

    Parameters
    ----------
    model
        Anything with the decode-model protocol: ``init_params(seed)``,
        ``state_shapes(slots, capacity) -> {name: (shape, dtype)}``
        (every leaf slot-major; pages-backed leaves named in
        ``state_capacity_axes``), and ``step(params, state, tokens,
        pos) -> (logits, new_state)`` with row ``i`` depending only on
        slot ``i`` (the join/leave-bitwise contract).  ``ToyLM`` and
        ``CellModel`` ship in this module.
    params : dict, optional
        Host parameter arrays (default ``model.init_params()``).
        Uploaded once, ledger-tagged ``serve_weights``.
    slots / page_tokens / max_pages : int, optional
        Lattice geometry: at most ``slots`` concurrent sequences
        (``MXNET_DECODE_SLOTS``, 8), KV paged in
        ``MXNET_DECODE_PAGE_TOKENS``-token pages (16), capacity
        ``page_tokens * max_pages`` tokens per sequence
        (``MXNET_DECODE_MAX_PAGES``, 8).
    max_queue : int, optional
        Bound on waiting (admitted, slotless) sequences — past it
        ``submit`` sheds with a typed ``Overloaded``
        (``MXNET_SERVE_MAX_QUEUE``).
    shed_policy : str, optional
        ``"deadline"`` (default, ``MXNET_SERVE_SHED_POLICY``) arms EDF
        shedding over remaining-token estimates; ``"depth"`` sheds on
        the queue bound only.
    """

    def __init__(self, model, params: Optional[dict] = None,
                 slots: Optional[int] = None,
                 page_tokens: Optional[int] = None,
                 max_pages: Optional[int] = None,
                 slot_buckets=None, page_buckets=None,
                 max_queue: Optional[int] = None,
                 shed_policy: Optional[str] = None,
                 eos: Optional[int] = None,
                 name: str = "decode", warmup: bool = True):
        import jax
        self.model = model
        self.name = str(name)
        self.max_slots = int(getenv("MXNET_DECODE_SLOTS", 8)) \
            if slots is None else int(slots)
        self.page_tokens = int(getenv("MXNET_DECODE_PAGE_TOKENS", 16)) \
            if page_tokens is None else int(page_tokens)
        self.max_pages = int(getenv("MXNET_DECODE_MAX_PAGES", 8)) \
            if max_pages is None else int(max_pages)
        if min(self.max_slots, self.page_tokens, self.max_pages) < 1:
            raise MXNetError("DecodeEngine needs slots/page_tokens/"
                             "max_pages >= 1")
        self.max_queue = int(getenv("MXNET_SERVE_MAX_QUEUE", 64)) \
            if max_queue is None else int(max_queue)
        policy = shed_policy or getenv("MXNET_SERVE_SHED_POLICY",
                                       "deadline")
        if policy not in ("depth", "deadline"):
            raise MXNetError(f"shed_policy must be 'depth' or "
                             f"'deadline', got {policy!r}")
        self.shed_policy = policy
        self.eos = eos
        self.spec = page_lattice(self.max_slots, self.max_pages,
                                 slot_buckets=slot_buckets,
                                 page_buckets=page_buckets)
        self.capacity = self.page_tokens * self.max_pages
        # reentrant: step() -> KV growth -> ensure_headroom -> arbiter
        # -> release_kv_pages re-enters on the same thread
        self._lock = _san.make_rlock("serving.decode.engine")
        self._closed = False
        self._seq_no = 0
        self._waiting: List[_Seq] = []
        self._slots: List[Optional[_Seq]] = []
        self._key: Optional[tuple] = None
        self._state = None          # device pytree, or None (no KV live)
        self._kv_holder = _PageTable()
        self._kv_bytes = 0
        self._edf = StepEDF()
        self._steps = 0
        self._admitted = 0
        self._completed = 0
        self._evicted = 0
        self._shed = 0
        self._expired = 0
        self._tokens_out = 0
        self._compiled: Dict[tuple, object] = {}
        self._ever_compiled: set = set()

        host = dict(params) if params is not None else model.init_params()
        pbytes = sum(int(_np.asarray(v).nbytes) for v in host.values())
        # ask-first (the PR 14 admission discipline): give the budget
        # arbiter a chance to evict colder victims before the upload;
        # past a hard budget the ledger's register() raises typed
        _memory.ensure_headroom(pbytes, why=f"decode.admit:{self.name}")

        def _to_dev(v):
            arr = jax.device_put(_np.asarray(v))
            return _memory.register(arr, tag="serve_weights")

        self._params = {k: _to_dev(v) for k, v in host.items()}
        self._jit = jax.jit(self._step_impl, donate_argnums=(0,))
        with _engines_lock:
            _ENGINES.add(self)
        if warmup:
            self.warmup()

    # -- compiled lattice ----------------------------------------------------
    def _step_impl(self, state, fresh, tokens, pos, params):
        import jax.numpy as jnp
        # slot reuse hygiene INSIDE the one dispatch: a slot whose
        # previous occupant retired since the last key transition still
        # holds its state rows — zero every freshly-joined slot's rows
        # (fresh[i] <=> sequence i has never been dispatched) so churn
        # stays bitwise-equal to solo decoding without an extra launch
        state = {n: jnp.where(
            jnp.reshape(fresh, (-1,) + (1,) * (leaf.ndim - 1)),
            jnp.zeros((), dtype=leaf.dtype), leaf)
            for n, leaf in state.items()}
        logits, new_state = self.model.step(params, state, tokens, pos)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return new_state, nxt

    def _state_shapes(self, key: tuple) -> Dict[str, tuple]:
        slots_b, pages_b = key
        return self.model.state_shapes(slots_b,
                                       pages_b * self.page_tokens)

    def _state_bytes(self, key: tuple) -> int:
        return sum(int(_np.prod(shp)) * _np.dtype(dt).itemsize
                   for shp, dt in self._state_shapes(key).values())

    def precompile(self, key: tuple):
        """AOT-build the donated step for one (slots, pages) key — the
        predictor's ``SERVE_COMPILES`` discipline verbatim: a fresh
        compile counts once, a rebuild of an evicted key counts as a
        readmission, and after ``warmup()`` traffic compiles nothing."""
        import jax
        key = tuple(key)
        with self._lock:
            compiled = self._compiled.get(key)
            if compiled is not None:
                return compiled
            shapes = self._state_shapes(key)
            state_avals = {n: jax.ShapeDtypeStruct(shp, dt)
                           for n, (shp, dt) in shapes.items()}
            iv = jax.ShapeDtypeStruct((key[0],), _np.int32)
            fv = jax.ShapeDtypeStruct((key[0],), _np.bool_)
            pv = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for n, v in self._params.items()}
            t0 = time.perf_counter()
            compiled = self._jit.lower(state_avals, fv, iv, iv,
                                       pv).compile()
            if _goodput.ENABLED:
                _goodput.attribute("recompile",
                                   time.perf_counter() - t0)
            from .. import base as _base
            readmission = (key in self._ever_compiled
                           and _base._COMPILE_CACHE_WIRED)
            if _metrics.ENABLED:
                if readmission:
                    _metrics.SERVE_READMITS.inc(kind="bucket")
                else:
                    _metrics.SERVE_COMPILES.inc()
                    if key in self._ever_compiled:
                        _metrics.SERVE_READMITS.inc(kind="bucket")
            self._ever_compiled.add(key)
            try:
                _introspect.note_program(
                    "decode_step", compiled=compiled,
                    label=bucket_label(key),
                    contracts={
                        "donate_argnums": (0,),
                        "donated_leaves": len(shapes),
                        "host_callbacks": 0,
                        "collectives": 0,
                    })
            except Exception as e:  # noqa: BLE001 — stats best-effort
                log.debug("decode_step note_program failed: %s", str(e))
            self._compiled[key] = compiled
            return compiled

    def warmup(self, keys=None) -> int:
        """Compile the whole lattice before traffic.  After this,
        per-step join/leave and page-boundary growth route between
        already-compiled keys — zero hot-path compiles."""
        done = 0
        for key in (keys if keys is not None else self.spec.all_keys()):
            self.precompile(tuple(key))
            done += 1
        return done

    # -- admission -----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int,
               deadline_ms: Optional[float] = None, priority: int = 0,
               tenant: str = "default") -> Future:
        """Admit one sequence; resolves to its generated token list.
        Sheds typed (``Overloaded`` with retry-after) on a full waiting
        queue, on an over-capacity request, or — policy ``deadline`` —
        when the EDF estimate already cannot meet ``deadline_ms``."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("decode submit needs a non-empty prompt")
        total = len(prompt) + int(max_new_tokens)
        if total > self.capacity:
            raise MXNetError(
                f"sequence needs {total} tokens > engine capacity "
                f"{self.capacity} (MXNET_DECODE_PAGE_TOKENS x "
                f"MXNET_DECODE_MAX_PAGES)")
        with self._lock:
            if self._closed:
                raise DecodeClosedError("DecodeEngine is closed")
            deadline = None if deadline_ms is None \
                else time.perf_counter() + float(deadline_ms) / 1e3
            seq = _Seq(self._seq_no, prompt, max_new_tokens, deadline,
                       priority, tenant, self.eos)
            self._seq_no += 1
            if len(self._waiting) >= self.max_queue:
                self._count_shed(tenant, "queue_full")
                retry = self._edf.eta_s(self._queued_tokens(),
                                        self._free_slots() or 1)
                raise Overloaded(
                    f"decode waiting queue full ({self.max_queue}, "
                    f"MXNET_SERVE_MAX_QUEUE); retry after "
                    f"~{retry:.2f}s", retry_after_s=retry)
            if self.shed_policy == "deadline" and deadline is not None:
                eta = self._edf.eta_s(
                    seq.remaining() + self._queued_tokens(),
                    max(1, self.max_slots))
                if time.perf_counter() + eta > deadline:
                    self._count_shed(tenant, "deadline_unmeetable")
                    raise Overloaded(
                        f"deadline {deadline_ms}ms unmeetable: EDF "
                        f"estimate ~{eta * 1e3:.1f}ms for "
                        f"{seq.remaining()} decode steps behind "
                        f"{self._queued_tokens()} queued tokens",
                        retry_after_s=eta)
            self._admitted += 1
            if _metrics.ENABLED:
                _metrics.SERVE_ADMITTED.inc(tenant=tenant)
            self._waiting.append(seq)
            # EDF order: priority first, earliest deadline within it
            self._waiting.sort(key=lambda s: (
                -s.priority,
                s.deadline if s.deadline is not None else float("inf"),
                s.sid))
            return seq.future

    def generate(self, prompt, max_new_tokens: int, **kw) -> List[int]:
        """Blocking convenience: submit + drive the engine until this
        sequence resolves (single-threaded tests and scripts)."""
        fut = self.submit(prompt, max_new_tokens, **kw)
        while not fut.done():
            if self.step() == 0 and not fut.done():
                break
        return fut.result()

    def _count_shed(self, tenant: str, reason: str) -> None:
        self._shed += 1
        if _metrics.ENABLED:
            _metrics.SERVE_SHED.inc(tenant=tenant, reason=reason)

    def _queued_tokens(self) -> int:
        return sum(s.remaining() for s in self._waiting)

    def _free_slots(self) -> int:
        return self.max_slots - sum(1 for s in self._slots
                                    if s is not None)

    # -- the decode step -----------------------------------------------------
    def _retire(self, seq: _Seq, exc: Optional[Exception] = None) -> None:
        """Free the sequence's slot and resolve its future (caller
        holds the lock)."""
        if seq.slot is not None and seq.slot < len(self._slots) \
                and self._slots[seq.slot] is seq:
            self._slots[seq.slot] = None
        seq.slot = None
        if seq.future.done():
            return
        if exc is not None:
            seq.future.set_exception(exc)
            return
        self._completed += 1
        seq.future.set_result(list(seq.generated))
        if _goodput.ENABLED:
            _goodput.serve_latency_sample(
                (time.perf_counter() - seq.t0) * 1e3)
        if _flight.ENABLED:
            _flight.record("decode_seq", "serving", seq.t0 * 1e6,
                           _flight.now_us(), trace_id=seq.trace_id)

    def _shed_and_expire(self, now: float) -> None:
        """Decode-step-granularity EDF: expire passed deadlines; when
        admitted work is waiting, preempt actives whose deadlines the
        remaining-tokens estimate can no longer meet (the slot goes to
        the earliest-deadline waiter on the admit pass that follows)."""
        for seq in [s for s in self._slots if s is not None]:
            if seq.deadline is None:
                continue
            if now > seq.deadline:
                self._expired += 1
                if _metrics.ENABLED:
                    _metrics.SERVE_EXPIRED.inc(tenant=seq.tenant)
                self._retire(seq, DeadlineExceeded(
                    f"sequence {seq.sid} deadline passed after "
                    f"{len(seq.generated)} generated token(s)"))
            elif self.shed_policy == "deadline" and self._waiting \
                    and self._edf.unmeetable(seq.deadline, now,
                                             seq.remaining()):
                self._count_shed(seq.tenant, "deadline_unmeetable")
                self._retire(seq, DeadlineExceeded(
                    f"sequence {seq.sid} preempted at decode-step "
                    f"granularity: {seq.remaining()} steps x "
                    f"~{self._edf.step_s() * 1e3:.1f}ms cannot meet "
                    f"its deadline and admitted work is waiting"))
        # drop waiters that already expired too — never dispatch them
        for seq in [s for s in self._waiting
                    if s.deadline is not None and now > s.deadline]:
            self._waiting.remove(seq)
            self._expired += 1
            if _metrics.ENABLED:
                _metrics.SERVE_EXPIRED.inc(tenant=seq.tenant)
            if not seq.future.done():
                seq.future.set_exception(DeadlineExceeded(
                    f"sequence {seq.sid} deadline passed in queue"))

    def _admit_waiting(self) -> None:
        """Fill free slots in EDF order (caller holds the lock)."""
        if not self._waiting:
            return
        if len(self._slots) < self.max_slots:
            self._slots.extend(
                [None] * (self.max_slots - len(self._slots)))
        for i in range(self.max_slots):
            if not self._waiting:
                break
            if self._slots[i] is None:
                seq = self._waiting.pop(0)
                seq.slot = i
                self._slots[i] = seq

    def _needed_key(self, compact: bool = False) -> Optional[tuple]:
        """Smallest lattice key covering the in-flight set.  Steady
        state routes on the highest OCCUPIED slot index (holes from
        retirements cost nothing until the bucket boundary, so no
        transition launches on every leave); ``compact=True`` routes on
        the live COUNT instead — what the set would need after a
        ``_transition`` compaction — which is what eviction must use,
        or reclaiming low slots could never shrink the buffers."""
        hi = -1
        live = 0
        max_pos = 0
        for i, s in enumerate(self._slots):
            if s is not None:
                hi = i
                live += 1
                max_pos = max(max_pos, s.pos + 1)
        if hi < 0:
            return None
        pages = -(-max_pos // self.page_tokens)  # ceil
        slots_need = live if compact else hi + 1
        return self.spec.route({"kv": (slots_need, pages)})

    def _transition(self, new_key: tuple) -> None:
        """Move live decode state onto ``new_key``'s buffers: compact
        occupied slots to the low indices, then pad/slice every leaf
        eagerly on device (a handful of launches on the RARE
        bucket-boundary crossing — steady-state steps stay at one).
        Growth asks the budget first; on refusal the longest actives
        are evicted typed until the remainder fits."""
        import jax.numpy as jnp
        new_bytes = self._state_bytes(new_key)
        grow = new_bytes - self._kv_bytes
        if grow > 0:
            if not _memory.ensure_headroom(
                    grow, why=f"decode.kv_grow:{self.name}"):
                self._evict_for_fit()
                new_key = self._needed_key(compact=True)
                if new_key is None:
                    self._drop_state()
                    return
                new_bytes = self._state_bytes(new_key)
        # compact: occupied slots move to 0..n-1 in slot order
        live = [s for s in self._slots if s is not None]
        if self._state is not None and live:
            perm = jnp.asarray([s.slot for s in live], dtype=jnp.int32)
            cap_axes = getattr(self.model, "state_capacity_axes", {})
            shapes = self._state_shapes(new_key)
            new_state = {}
            for n, leaf in self._state.items():
                taken = jnp.take(leaf, perm, axis=0)
                tgt, dt = shapes[n]
                pads = []
                for ax, d in enumerate(tgt):
                    have = taken.shape[ax]
                    if d < have:  # capacity shrink: keep the low side
                        taken = jnp.take(
                            taken, jnp.arange(d), axis=ax)
                        have = d
                    pads.append((0, d - have))
                new_state[n] = jnp.pad(taken, pads)
                del cap_axes  # capacity axis handled by shape math
                cap_axes = getattr(self.model, "state_capacity_axes", {})
            self._state = new_state
        else:
            shapes = self._state_shapes(new_key)
            self._state = {n: jnp.zeros(shp, dtype=dt)
                           for n, (shp, dt) in shapes.items()}
        for i, s in enumerate(live):
            s.slot = i
        self._slots = live + [None] * (self.max_slots - len(live))
        self._key = new_key
        self._register_kv(new_bytes)

    def _register_kv(self, nbytes: int) -> None:
        self._kv_bytes = int(nbytes)
        _memory.register(self._kv_holder, tag=KV_TAG,
                         nbytes=self._kv_bytes)

    def _drop_state(self) -> None:
        self._state = None
        self._key = None
        self._register_kv(0)

    def _evict_for_fit(self) -> None:
        """Budget refused KV growth: evict the longest actives (they
        force the page growth) typed until what remains fits the
        current buffers."""
        victims = sorted((s for s in self._slots if s is not None),
                         key=lambda s: -s.pos)
        for seq in victims:
            need = self._needed_key(compact=True)
            if need is None or (self._key is not None
                                and self._state_bytes(need)
                                <= self._kv_bytes):
                return
            self._evict_seq(seq, why="kv_grow")

    def _evict_seq(self, seq: _Seq, why: str) -> None:
        self._evicted += 1
        if _metrics.ENABLED:
            _metrics.SERVE_EVICTIONS.inc(kind="kv_pages",
                                         model=self.name)
            _metrics.DECODE_KV_EVICTIONS.inc()
        retry = self._edf.eta_s(self._queued_tokens() + seq.remaining(),
                                max(1, self.max_slots))
        self._retire(seq, SequenceEvicted(
            f"sequence {seq.sid} KV pages reclaimed under HBM "
            f"pressure ({why}); resubmit after ~{retry:.2f}s",
            retry_after_s=max(0.05, retry)))

    def release_kv_pages(self, deficit: float, why: str = "") -> float:
        """Reclaim ~``deficit`` ledger bytes of paged decode state —
        the ``serve_kv_pages`` arbiter hook (registry ``_make_room``
        phase 0).  Coldest first: waiting sequences hold no pages, so
        victims are actives with the *latest* deadlines / lowest
        priority / most work left; each fails typed with retry-after.
        Shrinks onto the smaller lattice key (or drops the buffers
        outright) so the freed bytes are REAL, then reports the
        measured ledger delta.

        Best-effort by contract: a busy engine lock (another thread
        mid-step) returns 0 instead of blocking — the arbiter moves on
        to cold buckets/models, and no registry-lock → engine-lock
        ordering edge can ever deadlock against an engine asking the
        budget for growth."""
        if not self._lock.acquire(blocking=False):
            return 0.0
        try:
            if self._state is None:
                return 0.0
            before = self._kv_bytes
            with _flight.phase_span("serve_evict", cat="serving",
                                    mem=True,
                                    labels={"model": self.name}):
                _fi_fire("serving.evict", model=self.name,
                         kind="kv_pages", why=why)
                victims = sorted(
                    (s for s in self._slots if s is not None),
                    key=lambda s: (
                        s.priority,
                        -(s.deadline if s.deadline is not None
                          else float("inf")),
                        -s.remaining()))
                for seq in victims:
                    if before - self._state_bytes_now() >= deficit:
                        break
                    self._evict_seq(seq, why=why or "arbiter")
                    need = self._needed_key(compact=True)
                    if need is None:
                        self._drop_state()
                    elif need != self._key:
                        self._transition(need)
            return float(before - self._kv_bytes)
        finally:
            self._lock.release()

    def _state_bytes_now(self) -> int:
        return self._kv_bytes if self._state is not None else 0

    @hot_path
    def step(self) -> int:
        """ONE decode step over the whole in-flight set: expire/shed
        (EDF), admit waiters into free slots, route the lattice key,
        then ONE donated dispatch — join/leave churn never changes the
        dispatch count.  Returns the number of active sequences
        advanced (0 = idle)."""
        with self._lock:
            if self._closed:
                raise DecodeClosedError("DecodeEngine is closed")
            now = time.perf_counter()
            self._shed_and_expire(now)
            self._admit_waiting()
            key = self._needed_key()
            if key is None:
                if self._state is not None:
                    self._drop_state()
                self._refresh_gauges()
                return 0
            if key != self._key or self._state is None:
                self._transition(key)
                key = self._key
                if key is None:
                    self._refresh_gauges()
                    return 0
            compiled = self.precompile(key)
            slots_b = key[0]
            tokens = _np.zeros((slots_b,), dtype=_np.int32)
            pos = _np.zeros((slots_b,), dtype=_np.int32)
            fresh = _np.zeros((slots_b,), dtype=_np.bool_)
            active = []
            for i in range(slots_b):
                s = self._slots[i]
                if s is None:
                    continue
                active.append(s)
                tokens[i] = s.prompt[s.pos] if s.pos < len(s.prompt) \
                    else s.generated[-1]
                pos[i] = s.pos
                # never dispatched: the slot's state rows may be a
                # retired predecessor's — the compiled step zeroes them
                fresh[i] = s.pos == 0
            t0 = time.perf_counter()
            with _flight.phase_span("decode_step", cat="serving",
                                    mem=True,
                                    labels={"bucket":
                                            bucket_label(key)}), \
                    _memory.oom_guard("serving.decode_step"):
                # chaos site BEFORE the dispatch: a raise rule models a
                # failed step with sequence state fully intact — the
                # caller retries step() and decode resumes bitwise
                # (tests/test_decode.py pins it); a delay rule is a
                # slow step feeding the EDF EWMA
                _fi_fire("serving.decode_step", step=self._steps,
                         active=len(active))
                if _metrics.ENABLED:
                    _metrics.XLA_LAUNCHES.inc(kind="decode")
                    _metrics.DECODE_STEPS.inc()
                state = self._state
                self._state = None  # donated: never reuse on failure
                try:
                    new_state, nxt = compiled(state, fresh, tokens,
                                              pos, self._params)
                except BaseException as e:
                    # the donated state may be consumed — poison the
                    # old mapping (typed DonatedBufferError on reuse
                    # under MXNET_SANITIZE) and fail every active
                    # sequence typed; waiting sequences survive
                    if _san.ENABLED:
                        _san.poison_mapping("decode_step", state)
                    self._drop_state()
                    err = MXNetError(
                        f"decode step failed mid-generation: "
                        f"{type(e).__name__}: {e}")
                    for s in active:
                        self._retire(s, err)
                    raise
                self._state = new_state
            # the per-step host sync is the decode CONTRACT, not an
            # accident: the sampled token is next step's input and the
            # join/leave scheduler's retire signal, so serving reads it
            # every step by design (continuous batching's irreducible
            # sync; the training hot paths this rule protects have no
            # such data dependence)
            # graft-lint: disable=host-sync
            nxt = _np.asarray(nxt)
            self._steps += 1
            step_s = time.perf_counter() - t0
            self._edf.observe(step_s)
            gen = 0
            for s in active:
                emitting = s.pos >= len(s.prompt) - 1
                s.pos += 1
                if emitting:
                    # host read of an already-synced numpy row (the
                    # asarray above); same justification
                    tok = int(nxt[s.slot])  # graft-lint: disable=host-sync
                    s.generated.append(tok)
                    gen += 1
                    done = len(s.generated) >= s.max_new or (
                        s.eos is not None and tok == s.eos)
                    if done:
                        self._retire(s)
            self._tokens_out += gen
            if _metrics.ENABLED:
                if gen:
                    _metrics.DECODE_TOKENS.inc(gen)
                if step_s > 0:
                    _metrics.DECODE_TOKENS_PER_S.set(
                        len(active) / max(step_s, 1e-9))
            self._refresh_gauges()
            if _flight.ENABLED:
                _flight.note("decode_step", step_s)
            return len(active)

    def drain(self, max_steps: int = 100000) -> int:
        """Step until idle (everything retired); returns steps run."""
        n = 0
        while n < max_steps:
            if self.step() == 0:
                break
            n += 1
        return n

    def _refresh_gauges(self) -> None:
        if not _metrics.ENABLED:
            return
        inflight = sum(1 for s in self._slots if s is not None)
        _metrics.DECODE_INFLIGHT.set(float(inflight))
        if self._key is not None and self._state is not None:
            slots_b, pages_b = self._key
            cap = slots_b * pages_b * self.page_tokens
            used = sum(s.pos + 1 for s in self._slots if s is not None)
            _metrics.DECODE_KV_OCCUPANCY.set(used / cap if cap else 0.0)
        else:
            _metrics.DECODE_KV_OCCUPANCY.set(0.0)

    # -- introspection / lifecycle -------------------------------------------
    def pending(self) -> int:
        with self._lock:
            return len(self._waiting) + sum(
                1 for s in self._slots if s is not None)

    def stats(self) -> dict:
        with self._lock:
            return {
                "steps": self._steps,
                "tokens": self._tokens_out,
                "admitted": self._admitted,
                "completed": self._completed,
                "evicted": self._evicted,
                "shed": self._shed,
                "expired": self._expired,
                "inflight": sum(1 for s in self._slots
                                if s is not None),
                "waiting": len(self._waiting),
                "key": self._key,
                "kv_bytes": self._kv_bytes,
                "step_ewma_s": self._edf.step_s(),
                "goodput": (self._completed / self._admitted)
                if self._admitted else 1.0,
            }

    def memory_stats(self) -> dict:
        with self._lock:
            return {
                "weights_bytes": sum(int(v.nbytes)
                                     for v in self._params.values()),
                "kv_bytes": self._kv_bytes,
            }

    def close(self) -> None:
        """Fail everything in flight typed, drop the compiled lattice,
        weights, and KV pages.  After close + the caller dropping its
        references, every ``serve_kv_pages`` ledger byte is back to
        baseline (the leak gate in tests/test_decode.py pins it)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            err = DecodeClosedError(
                "DecodeEngine closed before this sequence finished")
            for s in list(self._waiting):
                if not s.future.done():
                    s.future.set_exception(err)
            self._waiting.clear()
            for s in list(self._slots):
                if s is not None:
                    self._retire(s, err)
            self._slots = []
            self._drop_state()
            self._compiled.clear()
            self._params = {}
            if _metrics.ENABLED:
                _metrics.DECODE_INFLIGHT.set(0.0)
                _metrics.DECODE_KV_OCCUPANCY.set(0.0)
        with _engines_lock:
            _ENGINES.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# smoke gate: `python -m mxnet_tpu.serving.decode --smoke`
# ---------------------------------------------------------------------------
def _smoke() -> int:
    """The decode-smoke acceptance (< 60s, CPU): mixed-length traffic
    with per-step join/leave over a warmed lattice must hold exactly
    ONE dispatch per decode step and ZERO post-warmup compiles, and
    every admitted sequence must finish."""
    model = ToyLM(vocab=32, dim=8, window=4)
    eng = DecodeEngine(model, slots=4, page_tokens=4, max_pages=4,
                       name="smoke")
    try:
        compiles0 = _metrics.SERVE_COMPILES.value
        launches0 = _metrics.XLA_LAUNCHES.get(kind="decode")
        rng = _np.random.RandomState(0)
        futs = []
        # staggered mixed-length admission: the in-flight set churns
        # every few steps
        pending = [([int(t) for t in rng.randint(0, 32, size=n)], m)
                   for n, m in [(2, 3), (5, 8), (1, 12), (3, 2),
                                (7, 5), (2, 9), (4, 4), (1, 6)]]
        steps = 0
        while pending or eng.pending():
            for _ in range(2):
                if pending:
                    p, m = pending.pop(0)
                    futs.append(eng.submit(p, m))
            if eng.step() > 0:
                steps += 1
        outs = [f.result(timeout=5) for f in futs]
        launches = _metrics.XLA_LAUNCHES.get(kind="decode") - launches0
        compiles = _metrics.SERVE_COMPILES.value - compiles0
        ok = (launches == steps and compiles == 0
              and all(len(o) > 0 for o in outs)
              and eng.stats()["completed"] == len(futs))
        print(json.dumps({
            "decode_smoke": bool(ok),
            "steps": steps,
            "dispatches": launches,
            "post_warmup_compiles": compiles,
            "sequences": len(outs),
            "tokens": sum(len(o) for o in outs),
        }))
        if not ok:
            print("decode-smoke FAILED: dispatches != steps, a "
                  "post-warmup compile, or an unfinished sequence",
                  file=sys.stderr)
            return 1
        return 0
    finally:
        eng.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="mxnet_tpu.serving.decode")
    ap.add_argument("--smoke", action="store_true",
                    help="run the decode-smoke acceptance gate")
    args = ap.parse_args(argv)
    if args.smoke:
        return _smoke()
    ap.print_help()
    return 0


if __name__ == "__main__":
    sys.exit(main())
