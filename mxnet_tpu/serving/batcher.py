"""Dynamic micro-batching for the serving fast path.

Steady-state serving traffic is many small concurrent requests; each
one dispatched alone wastes the accelerator (a TPU matmul at batch 1
runs at the same step latency as batch 16).  The micro-batcher is the
standard serving answer (TF-Serving's BatchingSession shape): a request
queue plus one dispatcher thread that coalesces whatever arrived within
`max_wait_ms` (or until `max_batch` rows) into ONE padded bucket
dispatch, then scatters the output rows back to the callers' futures.

Latency contract: a lone request waits at most `max_wait_ms` beyond its
own dispatch; under load the queue drains continuously and the wait
converges to zero (the previous dispatch IS the wait).
"""
from __future__ import annotations

import contextlib
import os as _os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as _np

from ..analysis import hot_path, sanitizer as _san
from ..autotune import decisions as _decisions
from ..base import MXNetError, getenv
from ..faultinject import fire as _fi_fire
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from .buckets import covering_bucket, pad_to_shape

__all__ = ["MicroBatcher", "BatcherClosedError", "BatcherDeadError",
           "GenerativeRouteError", "stack_requests",
           "record_group_queue_wait", "group_trace_scope"]


def record_group_queue_wait(group, t_dispatch_us: float) -> None:
    """Flight-record each request's queue-wait (submit t0 → dispatch
    start) under its OWN trace id.  Shared by both dispatchers
    (`MicroBatcher` / `ResilientServer`) so the queue-wait semantics
    and id scheme cannot drift apart."""
    for r in group:
        _flight.record("serve_queue_wait", "serving", r.t0 * 1e6,
                       t_dispatch_us, trace_id=r.trace_id)


def group_trace_scope(group):
    """Thread-local trace scope carrying the group's JOINED ids — the
    pad/dispatch/slice spans recorded inside are joinable against every
    member request (single-request group: its id verbatim)."""
    return _flight.trace_scope(
        _flight.join_ids([r.trace_id for r in group]))


class GenerativeRouteError(MXNetError):
    """A generative (multi-token decode) request reached the
    request-coalescing tier.  Refused LOUDLY by design: one long
    generation would pin its whole coalesced micro-batch group for its
    full output length (the `rnn/` + BucketingModule hostage path) —
    route generation through `serving.decode.DecodeEngine`, which
    admits and retires sequences per decode STEP (continuous batching,
    docs/decode_serving.md)."""


class BatcherClosedError(MXNetError):
    """The batcher/server was closed before this request could be
    dispatched (or before it could be submitted)."""


class BatcherDeadError(MXNetError):
    """The dispatcher thread died.  Every pending future is failed with
    this — a dead worker must surface as a typed error, never as a
    caller hanging in Future.result() forever."""


class _Request:
    __slots__ = ("inputs", "rows", "future", "t0", "trace_id")

    def __init__(self, inputs: Dict[str, _np.ndarray]):
        self.inputs = inputs
        self.rows = next(iter(inputs.values())).shape[0]
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        # flight-recorder request id, minted at submit and carried
        # through queue-wait/pad/dispatch/slice so one request's spans
        # are joinable across threads in a timeline dump
        self.trace_id = _flight.new_trace_id() if _flight.ENABLED \
            else None


def stack_requests(spec, group) -> Dict[str, _np.ndarray]:
    """Stack a group of validated requests into one rectangular batch.
    Per-request sequence lengths may differ: each request pads up to the
    group's covering seq bucket BEFORE stacking (host-side copies; the
    device still sees one transfer + one dispatch).  Shared by
    `MicroBatcher` and `ResilientServer` — any object with `.inputs`
    dicts of equal key sets works."""
    names = list(group[0].inputs)
    stacked = {}
    for n in names:
        parts = [r.inputs[n] for r in group]
        ax = spec.seq_axes.get(n)
        if ax is not None and len({p.shape[ax] for p in parts}) > 1:
            tgt = covering_bucket(spec.seq_buckets,
                                  max(p.shape[ax] for p in parts))
            parts = [pad_to_shape(
                p, p.shape[:ax] + (tgt,) + p.shape[ax + 1:])
                for p in parts]
        stacked[n] = parts[0] if len(parts) == 1 else \
            _np.concatenate(parts, axis=0)
    return stacked


class MicroBatcher:
    """Coalesces concurrent `submit()`s into bucket-sized dispatches.

    Parameters
    ----------
    predictor : BucketedPredictor
        The AOT-compiled serving executor requests are routed through.
    max_wait_ms : float
        How long the dispatcher holds an open batch for more arrivals
        (default `MXNET_SERVE_MAX_WAIT_MS`, 2 ms).  0 disables
        coalescing-by-time: each drain takes only what already queued.
    max_batch : int
        Row cap per coalesced dispatch (default `MXNET_SERVE_MAX_BATCH`,
        else the predictor's largest batch bucket).
    """

    def __init__(self, predictor, max_wait_ms: Optional[float] = None,
                 max_batch: Optional[int] = None):
        self._pred = predictor
        if max_wait_ms is None:
            # ctor arg > MXNET_SERVE_MAX_WAIT_MS env pin > persisted
            # autotune decision (derived from the dispatch EWMA) > 2 ms
            decided = None
            if "MXNET_SERVE_MAX_WAIT_MS" not in _os.environ \
                    and _decisions.ENABLED:
                sig = getattr(getattr(predictor, "spec", None),
                              "signature", None)
                if sig is not None:
                    decided = _decisions.knob(sig, "serve_max_wait_ms",
                                              None)
            max_wait_ms = getenv("MXNET_SERVE_MAX_WAIT_MS", 2.0) \
                if decided is None else float(decided)
        self._max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        # the documented default chain: ctor arg > MXNET_SERVE_MAX_BATCH
        # > largest bucket (graft-lint env-sync found the env leg was
        # promised by docs/env_var.md but never read)
        if max_batch is None:
            max_batch = getenv("MXNET_SERVE_MAX_BATCH",
                               int(predictor.spec.max_batch))
        self._max_batch = int(max_batch)
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pending: _Request = None  # displaced overflow, leads next group
        # guards the pending slot: the dispatcher writes it while
        # close(timeout) (after a timed-out join) and _die() must be
        # able to claim it and fail its future instead of leaving the
        # caller hanging
        self._pending_lock = _san.make_lock("serving.batcher.pending")
        self._closed = False
        # set (under _pending_lock) once close() has swept the pending
        # slot: from then on the dispatcher must fail a displaced
        # request itself — parking it would orphan it.  Before the
        # sweep, parking during a graceful close is correct: the
        # dispatcher drains the slot before exiting
        self._swept = False
        self._fatal: Exception = None  # dispatcher-death cause
        # serializes the closed-check+enqueue against close(): without
        # it a submit() could enqueue after close() drained, leaving its
        # future unresolved forever.  Lock order (sanitizer-pinned):
        # submit -> pending, never the reverse
        self._submit_lock = _san.make_lock("serving.batcher.submit")
        self._thread = threading.Thread(
            target=self._loop, name="mxnet-serve-batcher", daemon=True)
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def submit(self, max_new_tokens: Optional[int] = None,
               **inputs) -> Future:
        """Enqueue one request; resolves to the list of output arrays
        (rows matching this request).  Never blocks on model execution:
        oversized requests ride the dispatcher thread too (dispatched
        alone; predict() chunks them over the largest bucket).  A
        malformed request fails ITS OWN future at enqueue time — it is
        never coalesced, so it cannot poison a group of well-formed
        requests that arrived in the same wait window.

        Output-shape note (seq-bucketed models): outputs come back at
        the dispatched bucket's width — for a coalesced group that is
        the GROUP's covering seq bucket, which may exceed the bucket
        the same request would route to solo.  Consumers slice by their
        request's true sequence length (valid-region values are
        identical either way; docs/inference.md)."""
        if max_new_tokens is not None:
            # raised in the CALLER's thread, not failed on the future:
            # this is a routing bug at the call site, and the hostage
            # path it would reintroduce (regression-pinned in
            # tests/test_decode.py) must never be one silent drop away
            raise GenerativeRouteError(
                f"max_new_tokens={max_new_tokens}: generative decode "
                f"must not ride the request-coalescing micro-batcher — "
                f"one long sequence would hold its whole coalesced "
                f"group hostage.  Use serving.decode.DecodeEngine "
                f"(per-step join/leave) or BucketingModule.generate")
        try:
            # normalization can fail too (unknown input name, empty
            # request) — every malformed-request shape must land on the
            # returned future as a descriptive MXNetError, never escape
            # as a raw KeyError in the caller's thread
            self._pred._check_names(inputs)
            req = _Request({n: self._pred._as_host(n, v)
                            for n, v in inputs.items()})
            self._pred._check_request(req.inputs)
            if _flight.ENABLED:
                # caller-thread anchor span: the request's trace id now
                # exists on BOTH sides of the thread hop (submit here,
                # queue-wait/pad/dispatch/slice on the dispatcher)
                _flight.record("serve_submit", "serving", req.t0 * 1e6,
                               _flight.now_us(), trace_id=req.trace_id)
        except Exception as e:  # noqa: BLE001 — delivered to caller
            f = Future()
            f.set_exception(e)
            return f
        with self._submit_lock:
            # atomic closed-check + enqueue: anything enqueued here is
            # ahead of close()'s sentinel, so the dispatcher serves it
            # (and _die() drains under the same lock, so nothing can
            # slip into the queue after a dead worker's final sweep)
            if self._closed:
                raise BatcherClosedError("MicroBatcher is closed")
            if self._fatal is not None:
                raise BatcherDeadError(
                    f"MicroBatcher worker died: {self._fatal}")
            self._queue.put(req)
        if _metrics.ENABLED:
            _metrics.SERVE_QUEUE_DEPTH.set(self._queue.qsize())
        return req.future

    def predict(self, **inputs) -> List[_np.ndarray]:
        """Blocking submit — the drop-in replacement for
        `predictor.predict` that rides the coalesced path."""
        return self.submit(**inputs).result()

    def close(self, timeout: float = 5.0) -> None:
        """Drain and stop the dispatcher thread.  Requests still queued
        (or displaced into the pending slot) when the worker exits — or
        when the join times out because a dispatch is hung — fail with a
        typed ``BatcherClosedError`` instead of hanging their caller's
        ``Future.result()`` forever; later ``submit()``s raise
        immediately."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)  # wake the dispatcher
        self._thread.join(timeout)
        alive = self._thread.is_alive()  # join timed out mid-dispatch
        leftovers = []
        with self._pending_lock:
            # the slot lock makes the claim safe even while the
            # dispatcher is alive mid-dispatch: it fails (rather than
            # parks) displaced requests once _swept is set
            self._swept = True
            if self._pending is not None:
                leftovers.append(self._pending)
                self._pending = None
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                leftovers.append(r)
        if alive:
            # the drain above may have eaten the close sentinel; re-arm
            # it so the still-running dispatcher exits instead of
            # blocking in queue.get() forever when its dispatch ends
            self._queue.put(None)
        for r in leftovers:
            if not r.future.done():
                r.future.set_exception(
                    BatcherClosedError("MicroBatcher closed before "
                                       "dispatch"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher side -----------------------------------------------------
    def _take_group(self) -> Optional[List[_Request]]:
        """Block for the first request, then hold the batch open until
        max_wait elapses or max_batch rows have arrived."""
        with self._pending_lock:
            first, self._pending = self._pending, None
        if first is None:
            first = self._queue.get()
            if first is None:
                return None
        group, rows = [first], first.rows
        deadline = time.perf_counter() + self._max_wait_s
        while rows < self._max_batch:
            remaining = deadline - time.perf_counter()
            try:
                nxt = self._queue.get(
                    timeout=remaining if remaining > 0 else None,
                    block=remaining > 0)
            except queue.Empty:
                break
            if nxt is None:
                self._queue.put(None)  # re-post the close sentinel
                break
            if rows + nxt.rows > self._max_batch:
                # would overflow the largest bucket: dispatch what we
                # have; hold the displaced request in the pending slot so
                # it LEADS the next group (re-queueing would push it to
                # the FIFO tail, starving large requests behind a steady
                # stream of small ones)
                with self._pending_lock:
                    if self._swept:
                        # close() already swept the slot: fail the
                        # displaced request now, or nobody ever will
                        # (a merely-closing batcher still drains — a
                        # request enqueued before close() is served)
                        if not nxt.future.done():
                            nxt.future.set_exception(BatcherClosedError(
                                "MicroBatcher closed before dispatch"))
                    else:
                        self._pending = nxt
                break
            group.append(nxt)
            rows += nxt.rows
        if _metrics.ENABLED:
            _metrics.SERVE_QUEUE_DEPTH.set(self._queue.qsize())
        return group

    @hot_path
    def _dispatch_group(self, group: List[_Request]) -> None:
        fl = _flight.ENABLED
        if fl:
            record_group_queue_wait(group, _flight.now_us())
        scope = group_trace_scope(group) if fl \
            else contextlib.nullcontext()
        try:
            with scope:
                with _flight.phase_span("serve_stack", cat="serving"):
                    stacked = stack_requests(self._pred.spec, group)
                # the routed private path: request accounting happens
                # HERE, per caller (predict() would count the stacked
                # batch as one request and fold queue wait out of the
                # latency histogram)
                outs = self._pred._predict_routed(stacked)
            lo = 0
            for r in group:
                # done() guard: close(timeout) may have already failed
                # this future while a long dispatch (first-bucket
                # compile) overran the join — an unguarded set_result
                # would raise InvalidStateError and poison the rest of
                # the group
                if not r.future.done():
                    r.future.set_result(
                        [o[lo:lo + r.rows] for o in outs])
                lo += r.rows
            now = time.perf_counter()
            if _metrics.ENABLED:
                _metrics.SERVE_REQUESTS.inc(len(group))
                for r in group:
                    _metrics.SERVE_LATENCY_SECONDS.observe(
                        now - r.t0, exemplar=r.trace_id)
                _metrics.SERVE_COALESCED_ROWS.set(
                    sum(r.rows for r in group))
            if fl:
                # slow-request watchdog: end-to-end latency vs EWMA
                for r in group:
                    _flight.note("serve_request", now - r.t0)
        except Exception as e:  # noqa: BLE001 — failures go to callers
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)

    def _loop(self) -> None:
        group = None
        try:
            while True:
                group = self._take_group()
                if group is None:
                    return
                # chaos site: a raise rule here kills the worker thread
                # — the death path below must fail every in-flight and
                # queued future with a typed error, never hang callers
                _fi_fire("serving.batcher")
                self._dispatch_group(group)
                group = None
                if self._closed and self._queue.empty() \
                        and self._pending is None:
                    return
        except BaseException as e:  # noqa: BLE001 — worker death
            # swallow after cleanup: the cause is recorded in _fatal
            # (submit raises it), every future failed typed, and the
            # thread exits — re-raising would only spam the thread
            # excepthook
            self._die(e, group)
            import logging
            logging.getLogger(__name__).error(
                "MicroBatcher worker died: %r", e)

    def _die(self, exc: BaseException, group) -> None:
        """Dispatcher-death cleanup: record the cause (submit() raises
        it from now on), then fail the current group plus everything
        queued/pending.  Runs under _submit_lock so no submit() can
        slip a request into the queue after the final sweep."""
        err = BatcherDeadError(
            f"MicroBatcher worker died: {type(exc).__name__}: {exc}")
        reqs = list(group or [])
        with self._submit_lock:
            self._fatal = exc if isinstance(exc, Exception) \
                else RuntimeError(repr(exc))
            with self._pending_lock:
                if self._pending is not None:
                    reqs.append(self._pending)
                    self._pending = None
            while True:
                try:
                    r = self._queue.get_nowait()
                except queue.Empty:
                    break
                if r is not None:
                    reqs.append(r)
        for r in reqs:
            if not r.future.done():
                r.future.set_exception(err)
