"""mxnet_tpu.serving — the inference fast path.

Four layers, composable (docs/inference.md and
docs/serving_resilience.md are the guides):

  - `BucketSpec` / `buckets` — the padded shape-bucket lattice
    (pow2-derived, `MXNET_SERVE_BUCKETS` / `MXNET_SERVE_SEQ_BUCKETS`);
  - `BucketedPredictor` — AOT-compiled executables per bucket
    (`jax.jit(...).lower(...).compile()`), `warmup()` for zero
    hot-path compiles, donated input buffers, persistent compile cache
    via `MXNET_COMPILE_CACHE_DIR`;
  - `MicroBatcher` — dynamic micro-batching: concurrent requests
    coalesce into one covering-bucket dispatch
    (`MXNET_SERVE_MAX_WAIT_MS` / `MXNET_SERVE_MAX_BATCH`);
  - `ResilientServer` — the resilience tier: per-tenant admission
    control with bounded priority queues (`MXNET_SERVE_MAX_QUEUE`),
    deadline-aware scheduling + load shedding
    (`MXNET_SERVE_SHED_POLICY`, typed `Overloaded` /
    `DeadlineExceeded`), and a `healthz()`/`readyz()` surface fed from
    the metrics registry.  Failure behavior is testable via
    `mxnet_tpu.faultinject`.
  - `ModelRegistry` — N models in one process under an HBM budget
    (`MXNET_HBM_BUDGET_MB`, `MXNET_SERVE_MAX_MODELS`): LRU eviction of
    cold buckets then cold models (`MXNET_SERVE_EVICT_POLICY`),
    restart-free readmission via the persistent compile cache, a typed
    degradation ladder ending in `ModelUnavailable` with retry-after,
    and tenant→model routing through each model's bounded queues
    (docs/multi_model.md).

Every request is flight-recorded end to end (ISSUE 8,
docs/observability.md): a trace_id minted at submit rides through
submit/admission -> queue-wait -> pad -> dispatch -> slice phase spans
across the batcher/scheduler threads, the serving latency histogram
carries per-bucket exemplar trace ids, and a slow-request watchdog
auto-dumps a Perfetto-loadable timeline on anomaly
(`observability.flight`; `MXNET_FLIGHT=0` disables).

Reference lineage: the C predict API + bucketing executors of MXNet
(arxiv 1512.01274), TVM's ahead-of-time deployment modules
(arxiv 1802.04799), and TF-Serving's health-checked batching workers
(arxiv 1605.08695).
"""
from . import buckets
from .buckets import (BucketSpec, covering_bucket, pad_to_shape,
                      parse_bucket_env, pow2_buckets)
from .predictor import BucketedPredictor, ModelEvictedError
from .batcher import (BatcherClosedError, BatcherDeadError, MicroBatcher,
                      stack_requests)
from . import resilience
from .resilience import DeadlineExceeded, Overloaded, ResilientServer
from . import registry
from .registry import ModelRegistry, ModelUnavailable
from . import decode
from .decode import (CellModel, DecodeEngine, GenerativeRouteError,
                     SequenceEvicted, ToyLM)

__all__ = ["BucketSpec", "BucketedPredictor", "MicroBatcher",
           "ResilientServer", "Overloaded", "DeadlineExceeded",
           "BatcherClosedError", "BatcherDeadError", "buckets",
           "resilience", "covering_bucket", "pad_to_shape",
           "parse_bucket_env", "pow2_buckets", "stack_requests",
           "registry", "ModelRegistry", "ModelUnavailable",
           "ModelEvictedError", "decode", "DecodeEngine", "ToyLM",
           "CellModel", "GenerativeRouteError", "SequenceEvicted"]
