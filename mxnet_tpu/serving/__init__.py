"""mxnet_tpu.serving — the inference fast path.

Three layers, composable (docs/inference.md is the guide):

  - `BucketSpec` / `buckets` — the padded shape-bucket lattice
    (pow2-derived, `MXNET_SERVE_BUCKETS` / `MXNET_SERVE_SEQ_BUCKETS`);
  - `BucketedPredictor` — AOT-compiled executables per bucket
    (`jax.jit(...).lower(...).compile()`), `warmup()` for zero
    hot-path compiles, donated input buffers, persistent compile cache
    via `MXNET_COMPILE_CACHE_DIR`;
  - `MicroBatcher` — dynamic micro-batching: concurrent requests
    coalesce into one covering-bucket dispatch
    (`MXNET_SERVE_MAX_WAIT_MS` / `MXNET_SERVE_MAX_BATCH`).

Reference lineage: the C predict API + bucketing executors of MXNet
(arxiv 1512.01274) and TVM's ahead-of-time deployment modules
(arxiv 1802.04799).
"""
from . import buckets
from .buckets import (BucketSpec, covering_bucket, pad_to_shape,
                      parse_bucket_env, pow2_buckets)
from .predictor import BucketedPredictor
from .batcher import MicroBatcher

__all__ = ["BucketSpec", "BucketedPredictor", "MicroBatcher", "buckets",
           "covering_bucket", "pad_to_shape", "parse_bucket_env",
           "pow2_buckets"]
