"""Shape-bucket geometry for the inference fast path.

The serving problem the bucket set solves: XLA specializes one
executable per exact input shape, so a naive serving surface recompiles
on every unseen batch size / sequence length — a multi-second stall on
the request path.  The fix is the reference MXNet bucketing-executor
design (arxiv 1512.01274 §4; `module/bucketing_module.py`) applied to
serving: compile a SMALL FIXED SET of padded shape buckets ahead of
time, then route every request to the smallest covering bucket.

Bucket derivation follows `ndarray/sparse.py`'s pow2 rule
(`1 << (n - 1).bit_length()`): ascending powers of two up to the pow2
ceiling of the declared maximum, overridable via `MXNET_SERVE_BUCKETS`
(batch) and `MXNET_SERVE_SEQ_BUCKETS` (sequence).  Padding waste is
bounded at <50% per axis by construction; the compile count is
O(log max) per axis.
"""
from __future__ import annotations

import os
from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as _np

from ..autotune import decisions as _decisions
from ..base import MXNetError

__all__ = ["pow2_buckets", "parse_bucket_env", "covering_bucket",
           "pad_to_shape", "BucketSpec", "observed_traffic",
           "page_lattice"]

# -- observed shape traffic (the autotune lattice feed) ----------------------
#: bounded ring of request batch sizes seen by BucketSpec.route — what
#: autotune.sweep.lattice_from_traffic derives a measured lattice from.
#: Recorded only while MXNET_AUTOTUNE is on (one boolean on the route
#: path otherwise); bounded, so an unattended server can't grow it.
_TRAFFIC_MAX = 4096
_traffic: deque = deque(maxlen=_TRAFFIC_MAX)


def observed_traffic() -> Tuple[int, ...]:
    """Request batch sizes observed by routing since process start
    (bounded ring, newest last) — feed for the tuner's
    ``lattice_from_traffic``."""
    return tuple(_traffic)


def pow2_buckets(max_n: int, lo: int = 1) -> List[int]:
    """Ascending powers of two from `lo` through the pow2 ceiling of
    `max_n` (the `ndarray/sparse.py:323` rule generalized to a ladder)."""
    if max_n < 1:
        raise MXNetError(f"bucket maximum must be >= 1, got {max_n}")
    lo = max(1, int(lo))
    out, b = [], lo
    while b < max_n:
        out.append(b)
        b <<= 1
    out.append(b)
    return out


def parse_bucket_env(name: str) -> Optional[List[int]]:
    """Parse `MXNET_SERVE_BUCKETS`-style env: a comma list of ints
    (e.g. "1,4,16,64").  Returns None when unset/empty; raises loudly on
    malformed values (a silently-ignored typo here would reintroduce the
    hot-path recompiles the bucket set exists to prevent)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        vals = sorted({int(tok) for tok in raw.replace(";", ",").split(",")
                       if tok.strip()})
    except ValueError:
        raise MXNetError(f"{name}={raw!r}: expected a comma list of ints")
    if not vals or vals[0] < 1:
        raise MXNetError(f"{name}={raw!r}: buckets must be positive ints")
    return vals


def covering_bucket(buckets: Sequence[int], n: int) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds every bucket (the
    caller chunks over the largest bucket)."""
    for b in buckets:  # buckets are sorted ascending and short (~log max)
        if b >= n:
            return b
    return None


def bucket_label(key: tuple) -> str:
    """Compact stable label for a bucket key in flight-recorder records
    ("8" batch-only, "8x128" batch x seq, "chunked" for oversized
    requests riding the largest bucket).  Cardinality is bounded by the
    lattice — one label per bucket, ever — so it is safe to attach to
    timeline spans and summaries."""
    if key and key[0] is None:
        return "chunked"
    return "x".join(str(k) for k in key)


def pad_to_shape(arr: _np.ndarray, shape: Tuple[int, ...]) -> _np.ndarray:
    """Zero-pad a host array up to `shape` (every dim of `arr` must be
    <= the target).  Host-side on purpose: requests arrive from the RPC
    boundary as host memory (MXPredSetInput parity), and padding before
    the single device transfer keeps serving at one XLA dispatch per
    batch — a device-side pad would cost an extra program launch."""
    if tuple(arr.shape) == tuple(shape):
        return _np.ascontiguousarray(arr)
    if len(arr.shape) != len(shape) or \
            any(a > s for a, s in zip(arr.shape, shape)):
        raise MXNetError(
            f"cannot pad {arr.shape} up to bucket shape {shape}")
    out = _np.zeros(shape, dtype=arr.dtype)
    out[tuple(slice(0, d) for d in arr.shape)] = arr
    return out


def page_lattice(max_slots: int, max_pages: int, slot_buckets=None,
                 page_buckets=None) -> "BucketSpec":
    """The (slots, pages) lattice continuous-batching decode routes
    over (`serving.decode.DecodeEngine`): axis 0 is decode SLOTS
    (concurrent sequences), the seq axis is KV PAGES — so one stock
    `BucketSpec` covers mixed-length generation the same way it covers
    mixed-size inference batches, and a sequence growing across a page
    boundary re-routes to a neighbouring precompiled key instead of
    compiling.  Explicit pow2 ladders are always passed down: the
    decode lattice is engine geometry (`MXNET_DECODE_*`), deliberately
    decoupled from the request-path `MXNET_SERVE_BUCKETS` pins and the
    autotuned serving lattice."""
    if max_slots < 1 or max_pages < 1:
        raise MXNetError(
            f"page_lattice needs max_slots/max_pages >= 1, got "
            f"({max_slots}, {max_pages})")
    return BucketSpec(
        {"kv": (max_slots, max_pages)},
        batch_buckets=list(slot_buckets) if slot_buckets
        else pow2_buckets(max_slots),
        seq_axes={"kv": 1},
        seq_buckets=list(page_buckets) if page_buckets
        else pow2_buckets(max_pages))


class BucketSpec:
    """The (batch, seq) bucket lattice one served model routes over.

    batch buckets cover axis 0 of every input; seq buckets (optional)
    cover one declared axis per sequence-bearing input (`seq_axes`:
    input name -> axis).  A bucket key is `(batch,)` or `(batch, seq)`.
    """

    def __init__(self, input_shapes: dict, batch_buckets=None,
                 seq_axes: Optional[dict] = None, seq_buckets=None):
        if not input_shapes:
            raise MXNetError("BucketSpec needs at least one input shape")
        self.input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self.seq_axes = dict(seq_axes or {})
        for name, ax in self.seq_axes.items():
            shp = self.input_shapes.get(name)
            if shp is None:
                raise MXNetError(f"seq_axes names unknown input '{name}'")
            if not 0 < ax < len(shp):
                raise MXNetError(
                    f"seq axis {ax} out of range for input '{name}' {shp}")
        batches = {s[0] for s in self.input_shapes.values()}
        if len(batches) != 1:
            raise MXNetError(
                f"inputs disagree on batch (axis 0) size: {input_shapes}")
        self.max_batch_hint = batches.pop()

        def _checked(buckets, what):
            # kwarg-provided ladders get the same validation the env
            # path enforces — a 0/negative bucket would compile a
            # degenerate executable and corrupt covering-bucket routing
            out = sorted(set(int(b) for b in buckets))
            if not out or out[0] < 1:
                raise MXNetError(
                    f"{what} buckets must be positive ints, got "
                    f"{list(buckets)}")
            return out

        # serving decisions key on the DECLARED bucket-spec shapes (not
        # trainable params — a served model is just its input surface)
        self.signature = _decisions.model_signature(
            sorted(self.input_shapes.items()),
            extra=("serving", tuple(sorted(self.seq_axes.items()))))
        # ladder precedence: ctor arg > MXNET_SERVE_BUCKETS env pin >
        # persisted autotune lattice (derived from observed traffic) >
        # blind pow2 ladder
        decided = None
        if batch_buckets is None \
                and "MXNET_SERVE_BUCKETS" not in os.environ \
                and _decisions.ENABLED:
            knob = _decisions.knob(self.signature, "serve_buckets", None)
            if knob:
                decided = [int(t) for t in str(knob).split(",")]
        self.batch_buckets = _checked(
            batch_buckets or parse_bucket_env("MXNET_SERVE_BUCKETS")
            or decided or pow2_buckets(self.max_batch_hint), "batch")
        if self.seq_axes:
            max_seq = max(self.input_shapes[n][ax]
                          for n, ax in self.seq_axes.items())
            self.seq_buckets = _checked(
                seq_buckets or parse_bucket_env("MXNET_SERVE_SEQ_BUCKETS")
                or pow2_buckets(max_seq), "seq")
        else:
            self.seq_buckets = None

    # -- routing ------------------------------------------------------------
    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    def all_keys(self) -> List[tuple]:
        if self.seq_buckets is None:
            return [(b,) for b in self.batch_buckets]
        return [(b, s) for b in self.batch_buckets
                for s in self.seq_buckets]

    def route(self, shapes: dict) -> tuple:
        """Smallest covering bucket key for one request's input shapes
        ({name: shape}).  Raises when the request exceeds the largest
        seq bucket; batch overflow is the caller's chunking problem and
        reported via a None batch component."""
        rows = {s[0] for s in shapes.values()}
        if len(rows) != 1:
            raise MXNetError(f"inputs disagree on batch size: {shapes}")
        n = rows.pop()
        if _decisions.ENABLED:
            _traffic.append(int(n))
        b = covering_bucket(self.batch_buckets, n)
        if self.seq_buckets is None:
            return (b,)
        seq = 0
        for name, ax in self.seq_axes.items():
            if name in shapes:
                seq = max(seq, shapes[name][ax])
        s = covering_bucket(self.seq_buckets, seq)
        if s is None:
            raise MXNetError(
                f"sequence length {seq} exceeds the largest seq bucket "
                f"{self.seq_buckets[-1]}; widen MXNET_SERVE_SEQ_BUCKETS")
        return (b, s)

    def bucket_input_shapes(self, key: tuple) -> dict:
        """Concrete padded input shapes for one bucket key."""
        b = key[0]
        out = {}
        for name, shp in self.input_shapes.items():
            shp = (b,) + tuple(shp[1:])
            ax = self.seq_axes.get(name)
            if ax is not None:
                shp = shp[:ax] + (key[1],) + shp[ax + 1:]
            out[name] = shp
        return out

    def waste_fraction(self, key: tuple, shapes: dict) -> float:
        """Fraction of padded (dead) elements the bucket dispatch will
        compute over — the padding-waste serving gauge."""
        want = sum(int(_np.prod(s)) for s in shapes.values())
        got = sum(int(_np.prod(s))
                  for s in self.bucket_input_shapes(key).values())
        return 1.0 - (want / got) if got else 0.0
