"""Shape-bucketed AOT inference executor — the serving fast path.

What the naive path costs: `Predictor` compiles one executable per
EXACT input shape, so the first request at any unseen batch size or
sequence length pays a full XLA compile on the hot path (seconds), and
every request is its own dispatch.  This module is the TPU realization
of the reference design pair the ROADMAP's serving north star points
at — MXNet's bucketing executors (arxiv 1512.01274) and TVM's
ahead-of-time compiled deployment modules (arxiv 1802.04799):

  - a small fixed lattice of padded shape buckets (`buckets.BucketSpec`,
    pow2-derived, `MXNET_SERVE_BUCKETS` override);
  - each bucket AOT-compiled ONCE via `jax.jit(...).lower(...).compile()`
    — `warmup()` moves every compile off the request path;
  - JAX's persistent compilation cache (`MXNET_COMPILE_CACHE_DIR`) so a
    process restart re-loads executables from disk instead of
    recompiling;
  - requests pad on host into the bucket shape (one device transfer,
    ONE XLA dispatch per request/coalesced batch) and slice the valid
    rows back out;
  - the padded input buffer is donated to the executable
    (`donate_argnums`) — on TPU the input HBM block is released to the
    program instead of held across the call.
"""
from __future__ import annotations

import threading
import time
import warnings
from contextlib import nullcontext as _nullcontext
from typing import Dict, List, Optional, Sequence

import numpy as _np

import jax

from ..analysis import hot_path
from ..analysis import sanitizer as _sanitizer
from ..base import MXNetError, maybe_enable_compile_cache, np_dtype
from ..context import cpu
from ..faultinject import fire as _fi_fire
from ..ndarray import NDArray
from ..observability import flight as _flight
from ..observability import introspect as _introspect
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from ..observability.tracing import trace_span
from .. import symbol as sym_mod
from ..symbol import Symbol
from ..symbol.graph import GraphPlan
from .buckets import BucketSpec, bucket_label, pad_to_shape

__all__ = ["BucketedPredictor", "ModelEvictedError"]


class ModelEvictedError(MXNetError):
    """A dispatch/compile reached a predictor whose device weights are
    evicted.  The registry readmits at submit, so this surfacing to a
    caller means a request raced an eviction (or bypassed the registry)
    — readmit() and retry."""


class BucketedPredictor:
    """Forward-only serving executor over a fixed shape-bucket lattice.

    Parameters
    ----------
    symbol : Symbol or str
        The inference graph (a Symbol, or its JSON as from
        `Symbol.tojson()`).
    params : dict / bytes / str
        `{name: NDArray-or-numpy}` (optionally `arg:`/`aux:` prefixed),
        a serialized param blob (parsed in memory), or a param file
        path.
    input_shapes : dict
        `{input_name: shape}` — axis 0 is the batch axis; the declared
        sizes are the maxima the default pow2 bucket ladders are
        derived from.
    seq_axes : dict, optional
        `{input_name: axis}` marking a second bucketed (sequence) axis.
        Sequence padding is exact only for position-independent models
        (see docs/inference.md for the caveat).
    donate : bool
        Donate the padded input buffer to the compiled program
        (default True; a no-op on backends without donation support).
    """

    def __init__(self, symbol, params, input_shapes: Dict[str, tuple],
                 dev=None, batch_buckets=None, seq_axes=None,
                 seq_buckets=None, input_dtypes=None,
                 output_names: Optional[Sequence[str]] = None,
                 donate: bool = True, resident: bool = True):
        from ..predictor import load_param_payload, split_arg_aux
        maybe_enable_compile_cache()
        if isinstance(symbol, Symbol):
            sym = symbol
        else:
            sym = sym_mod.load_json(symbol)
        if output_names:
            internals = sym.get_internals()
            sym = sym_mod.Group([internals[n] for n in output_names])
        self._symbol = sym
        self._ctx = dev or cpu()
        self._plan = GraphPlan(sym)
        self._donate = bool(donate)

        # a dict payload stays host-side as-is (load_param_payload
        # would wrap numpy values in DEVICE NDArrays — a transient
        # second copy of the whole model that pollutes the HBM ledger
        # a multi-model budgeter admits against); blob/path payloads
        # still load through it, and the transient is dropped below
        # before the served weights allocate
        payload = dict(params) if isinstance(params, dict) \
            else load_param_payload(params)
        arg_params, aux_params = split_arg_aux(payload)
        arg_names = sym.list_arguments()
        self._input_names = [n for n in arg_names if n not in arg_params]
        for name in input_shapes:
            if name not in self._input_names:
                raise MXNetError(
                    f"'{name}' is not a free input of the symbol; free "
                    f"inputs: {self._input_names}")
        dev_j = self._ctx.jax_device()

        def _host_copy(v):
            # an OWNED copy, never an alias: np.asarray on a caller's
            # numpy array is no-copy, and registering caller-owned
            # buffers under our tag would misattribute them for as
            # long as the caller holds them (and retag ones the caller
            # already registered)
            arr = v.asnumpy() if isinstance(v, NDArray) else \
                _np.array(v, copy=True)
            # host twin of the served weights: the restart-free
            # readmission source after evict() — a reload costs one
            # device_put per array, never a training-checkpoint round
            # trip (ledger tag serve_host_params, space=host)
            return _memory.register_host(arr, tag="serve_host_params")

        # the host param payload outlives the device weights: evict()
        # drops the device copies (and the AOT executables) but keeps
        # this, so readmit() is a reload + cache-hit compile
        self._host_payload = (
            {k: _host_copy(v) for k, v in arg_params.items()},
            {k: _host_copy(v) for k, v in aux_params.items()})
        # drop any loader-made device NDArrays NOW — the served
        # weights below must be the payload's only device copy
        del payload, arg_params, aux_params

        def _to_dev(v):
            arr = jax.device_put(_np.asarray(v), dev_j)
            # HBM ledger: served weights are the long-lived buffers a
            # multi-model budgeter evicts against — always attributed
            return _memory.register(arr, tag="serve_weights")

        # one tuple holds the live (params, aux) pair: hot_reload swaps
        # it with a single reference assignment, so no reader can ever
        # see params of one checkpoint with aux of another.
        # resident=False constructs straight onto the weights_evicted
        # ladder rung — host payload only, NO device allocation, so a
        # registry can admit a model that does not currently fit the
        # HBM budget without transiently blowing that same budget
        self._closed = False
        # distinguishes a first admission from a true readmission:
        # only the latter counts in SERVE_READMITS
        self._was_evicted = False
        if resident:
            self._weights = (
                {k: _to_dev(v)
                 for k, v in self._host_payload[0].items()},
                {k: _to_dev(v)
                 for k, v in self._host_payload[1].items()})
            self._resident = True
        else:
            self._weights = ({}, {})
            self._resident = False
        self._input_dtypes = {
            n: np_dtype((input_dtypes or {}).get(n, "float32"))
            for n in input_shapes}

        self.spec = BucketSpec(input_shapes, batch_buckets=batch_buckets,
                               seq_axes=seq_axes, seq_buckets=seq_buckets)
        # serving must be deterministic across identical requests — a
        # fixed key, never the global stream (is_train=False consumes no
        # randomness in stock models anyway)
        self._rng = jax.random.PRNGKey(0)
        self._compiled: Dict[tuple, object] = {}
        self._extra: Dict[tuple, dict] = {}  # per-bucket zero placeholders
        # LRU clock per bucket (stamped at precompile and every
        # dispatch) + the set of keys EVER compiled in this process:
        # a rebuild of an evicted bucket is a readmission (a
        # persistent-cache hit when MXNET_COMPILE_CACHE_DIR is wired),
        # not an escape from the bucket set, so it must not count
        # against the stay-flat SERVE_COMPILES contract
        self._bucket_used: Dict[tuple, float] = {}
        self._ever_compiled: set = set()
        # per-bucket CompiledMemoryStats (memory.compiled_stats_dict
        # shape), filled at precompile — feeds readyz + the
        # SERVE_BUCKET_HBM_BYTES gauge (docs/memory.md)
        self._mem_stats: Dict[tuple, dict] = {}
        # compiles may be triggered concurrently by batcher + direct
        # callers; one lock keeps "compile each bucket once" true.  It
        # also guards the weights/payload lifecycle swaps (hot_reload
        # on the auto-reload thread vs evict/readmit/close from a
        # registry) — reentrant because evict() nests evict_bucket()
        from ..analysis import sanitizer as _san
        self._compile_lock = _san.make_rlock("serving.predictor.compile")

        plan = self._plan

        def _serve(data, extra, params, aux, key):
            merged = dict(params)
            merged.update(extra)
            merged.update(data)
            outs, _ = plan.run(merged, aux, key, False)
            return list(outs)

        self._jit = jax.jit(
            _serve, donate_argnums=(0,) if self._donate else ())

    @property
    def _params(self) -> dict:
        return self._weights[0]

    @property
    def _aux(self) -> dict:
        return self._weights[1]

    # -- compilation ---------------------------------------------------------
    def _placeholder_shapes(self, in_shapes: dict) -> dict:
        """Zero placeholders for free args not served as inputs (label
        heads of training symbols — MXPredCreate parity)."""
        missing = [n for n in self._input_names if n not in in_shapes]
        if not missing:
            return {}
        arg_shapes, _, _ = self._symbol.infer_shape_partial(**in_shapes)
        inferred = dict(zip(self._symbol.list_arguments(), arg_shapes or []))
        out = {}
        for name in missing:
            shp = inferred.get(name)
            if shp is None:
                raise MXNetError(
                    f"input '{name}' has no declared shape and shape "
                    f"inference could not determine one")
            out[name] = tuple(shp)
        return out

    def precompile(self, key: tuple):
        """AOT-compile one bucket (idempotent).  The compile happens via
        lower().compile() so it also lands in the persistent compilation
        cache when MXNET_COMPILE_CACHE_DIR is set."""
        if key in self._compiled:
            return self._compiled[key]
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            if not self._resident:
                raise ModelEvictedError(
                    "model weights are evicted — readmit() before "
                    "compiling/serving (a ModelRegistry does this at "
                    "submit; see docs/multi_model.md)")
            in_shapes = self.spec.bucket_input_shapes(key)
            extra = {n: _memory.register(jax.device_put(
                _np.zeros(s, _np.float32), self._ctx.jax_device()),
                tag="serve_weights")
                for n, s in self._placeholder_shapes(in_shapes).items()}
            data_avals = {n: jax.ShapeDtypeStruct(s, self._input_dtypes[n])
                          for n, s in in_shapes.items()}
            to_aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
            extra_avals = {k: to_aval(v) for k, v in extra.items()}
            param_avals = {k: to_aval(v) for k, v in self._params.items()}
            aux_avals = {k: to_aval(v) for k, v in self._aux.items()}
            # bucket padding is only sound for batch-major outputs
            # (valid rows slice back out on axis 0) — reject scalar /
            # non-batch-major outputs HERE with a clear error instead of
            # silently serving corrupted values (a batch-diluted mean,
            # a time-major RNN output) or crashing at slice time
            out_shapes = [o.shape for o in jax.eval_shape(
                self._jit, data_avals, extra_avals, param_avals,
                aux_avals, self._rng)]
            bad = [s for s in out_shapes
                   if len(s) < 1 or s[0] != key[0]]
            if bad:
                raise MXNetError(
                    f"output shapes {out_shapes} are not batch-major "
                    f"(axis 0 != bucket batch {key[0]}): this symbol "
                    f"cannot be served through bucket padding "
                    f"(docs/inference.md)")
            with warnings.catch_warnings():
                # CPU/odd backends report "donated buffers were not
                # usable" when no output aliases the input shape; the
                # donation is a best-effort HBM release, not a contract
                warnings.filterwarnings(
                    "ignore", message=".*donated buffers.*")
                _t0_compile = time.perf_counter()
                compiled = self._jit.lower(
                    data_avals, extra_avals, param_avals, aux_avals,
                    self._rng).compile()
            from ..observability import goodput as _goodput
            if _goodput.ENABLED:
                # measured XLA compile (or persistent-cache load) time
                # books as recompile badput: seconds a request spent
                # waiting on program build, not dispatch
                _goodput.attribute("recompile",
                                   time.perf_counter() - _t0_compile)
            from .. import base as _base
            readmission = (key in self._ever_compiled
                           and _base._COMPILE_CACHE_WIRED)
            if _metrics.ENABLED:
                if readmission:
                    # rebuilding an evicted bucket with the persistent
                    # compile cache warm: the lower().compile() above
                    # was a disk hit, not a fresh XLA compile — counted
                    # as a readmission so SERVE_COMPILES keeps meaning
                    # "requests escaped the bucket set"
                    _metrics.SERVE_READMITS.inc(kind="bucket")
                else:
                    _metrics.SERVE_COMPILES.inc()
                    if key in self._ever_compiled:
                        # evicted bucket rebuilt WITHOUT the persistent
                        # cache: a real recompile AND a readmission
                        _metrics.SERVE_READMITS.inc(kind="bucket")
            self._ever_compiled.add(key)
            # compiled cost + HBM table per bucket, straight from XLA's
            # own analyses — what serving this bucket COSTS before any
            # request runs.  note_program is the ONE compiled-stats
            # surface (ISSUE 13): it files the memory stats into the
            # HBM ledger's report()["compiled"] AND the program
            # registry; the label rides the bounded bucket lattice,
            # the flight recorder's bucket_label discipline.
            try:
                label = bucket_label(key)
                mem = _introspect.note_program(
                    "serve_bucket", compiled=compiled,
                    label=label).get("memory", {})
                if not mem and not _introspect.ENABLED:
                    # introspection off: keep the PR 9 stats path alive
                    mem = _memory.compiled_stats_dict(
                        compiled.memory_analysis())
                    if mem:
                        _memory.note_compiled("serve_bucket:" + label, mem)
            except Exception:  # noqa: BLE001 — stats are best-effort
                mem = {}
            if mem:
                self._mem_stats[key] = mem
                if _metrics.ENABLED:
                    _metrics.SERVE_BUCKET_HBM_BYTES.set(
                        mem["peak_bytes"], bucket=label)
            self._extra[key] = extra
            self._compiled[key] = compiled
            self._bucket_used[key] = time.monotonic()
            return compiled

    def warmup(self, keys=None) -> "BucketedPredictor":
        """Compile every bucket (or the given keys) ahead of traffic —
        after this, serving any request within the bucket set performs
        ZERO XLA compiles."""
        for key in (keys if keys is not None else self.spec.all_keys()):
            self.precompile(tuple(key))
        return self

    @property
    def num_compiled(self) -> int:
        return len(self._compiled)

    def memory_stats(self) -> dict:
        """Per-bucket compiled HBM costs + live weight bytes: the
        budgeting surface for a shared-HBM multi-model registry (and
        ``ResilientServer.readyz()``'s ``bucket_hbm`` detail).
        ``peak_bytes`` is XLA's own buffer-assignment high-water mark
        per bucket executable; ``weights_bytes`` is THIS instance's
        live served weights + bucket placeholders — per-model, so a
        multi-model budgeter sees what evicting this predictor would
        actually free (the process-wide ``serve_weights`` ledger tag
        sums over every predictor)."""
        # GIL-atomic snapshots first: precompile on another thread
        # (batcher, warmup) inserts new buckets concurrently; the inner
        # stat dicts are write-once at insert so copying them is safe
        stats = dict(self._mem_stats)
        resident = set(self._compiled)
        per_bucket = {}
        for k, v in sorted(stats.items()):
            d = dict(v)
            # evicted buckets keep their stats entry (it is the
            # registry's readmission cost estimate) but are flagged so
            # peak totals below only count executables that are LIVE
            d["resident"] = k in resident
            per_bucket[bucket_label(k)] = d
        live = [v for v in per_bucket.values() if v["resident"]]
        params, aux = self._weights
        weights = sum(_memory.nbytes_of(a) for d in (params, aux)
                      for a in d.values())
        weights += sum(_memory.nbytes_of(a)
                       for ph in dict(self._extra).values()
                       for a in ph.values())
        return {
            "buckets": per_bucket,
            "resident": self._resident,
            "peak_bytes_max": max(
                (v["peak_bytes"] for v in live), default=0),
            "peak_bytes_total": sum(v["peak_bytes"] for v in live),
            "weights_bytes": int(weights),
        }

    # -- serving -------------------------------------------------------------
    def _as_host(self, name: str, value) -> _np.ndarray:
        """Request payloads normalize to host numpy in the declared input
        dtype (the C predict API hands over host buffers; device-resident
        NDArrays are fetched — serving's contract is host-in/host-out)."""
        if isinstance(value, NDArray):
            value = value.asnumpy()
        arr = _np.asarray(value)
        dt = self._input_dtypes[name]
        if arr.dtype != dt:
            arr = arr.astype(dt)
        return arr

    def _served_names(self) -> list:
        return [n for n in self._input_names
                if n in self.spec.input_shapes]

    def _check_names(self, inputs) -> None:
        served = self._served_names()
        if set(inputs) != set(served):
            raise MXNetError(
                f"request needs exactly inputs {served}, got "
                f"{sorted(inputs)}")

    def _check_request(self, inputs: Dict[str, _np.ndarray]) -> None:
        """Validate one request's input set and geometry up front: exact
        served-input names, fixed (non-bucketed) dims matching the
        declared template, sequence inside the largest seq bucket, and
        one agreed batch size.  Raises MXNetError.  The micro-batcher
        runs this at submit() so a malformed request fails ALONE instead
        of poisoning the coalesced group it would have joined."""
        self._check_names(inputs)
        for n, a in inputs.items():
            tmpl = self.spec.input_shapes[n]
            if len(a.shape) != len(tmpl):
                raise MXNetError(
                    f"input '{n}': rank {len(a.shape)} != declared "
                    f"rank {len(tmpl)} {tmpl}")
            ax_seq = self.spec.seq_axes.get(n)
            for i in range(1, len(tmpl)):
                if i != ax_seq and a.shape[i] != tmpl[i]:
                    raise MXNetError(
                        f"input '{n}' dim {i} is {a.shape[i]}, declared "
                        f"{tmpl[i]} (only batch/seq axes may vary)")
        # one agreed batch size + seq inside the largest bucket
        self.spec.route({n: a.shape for n, a in inputs.items()})

    @hot_path
    def _dispatch(self, key: tuple, padded: dict) -> list:
        compiled = self.precompile(key)
        # snapshot the placeholders WITH the executable: a concurrent
        # registry bucket eviction between precompile and here drops
        # _extra[key]; one rebuild pass keeps the failure typed instead
        # of a KeyError poisoning the whole dispatch group
        extra = self._extra.get(key)
        if extra is None:
            compiled = self.precompile(key)
            extra = self._extra.get(key)
            if extra is None:
                raise ModelEvictedError(
                    f"bucket {key} evicted mid-dispatch — retry")
        self._bucket_used[key] = time.monotonic()  # LRU clock
        # the flight span opens BEFORE the chaos site: an injected
        # delay models a slow model under load, so it must show up as a
        # long serve_dispatch phase in the timeline — exactly what the
        # slow-request watchdog's auto-dump exists to attribute
        with _flight.phase_span("serve_dispatch", cat="serving",
                                labels={"bucket": bucket_label(key)},
                                mem=True), \
                _memory.oom_guard("serving.dispatch"):
            # chaos sites: delay = slow model under load, raise = failed
            # dispatch (surfaces to the caller/future); memory.oom = a
            # synthetic RESOURCE_EXHAUSTED exercising the post-mortem
            # (catch → ledger+ring dump → typed DeviceMemoryError)
            _fi_fire("serving.dispatch", key=key)
            _fi_fire("memory.oom", at="serving")
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(kind="serve")
                _metrics.SERVE_BATCHES.inc()
            # one read: a mid-call hot_reload can't tear the pair
            params, aux = self._weights
            if not params and not aux and not self._resident:
                raise ModelEvictedError(
                    "model weights were evicted between precompile and "
                    "dispatch — readmit() and retry")
            with trace_span("serve_dispatch", cat="serving"):
                try:
                    return compiled(padded, extra, params, aux,
                                    self._rng)
                except BaseException:
                    # MXNET_SANITIZE twin (ISSUE 15): with donation on,
                    # a failed dispatch may have consumed the padded
                    # input buffers — poison the batch dict in place so
                    # a retry that erroneously reuses it fails typed
                    # (DonatedBufferError) instead of serving deleted
                    # arrays.  One boolean test when off.
                    if self._donate and _sanitizer.ENABLED:
                        _sanitizer.poison_mapping("serve_dispatch",
                                                  padded)
                    raise

    @hot_path
    def _predict_routed(self, inputs: Dict[str, _np.ndarray]) -> list:
        shapes = {n: a.shape for n, a in inputs.items()}
        key = self.spec.route(shapes)
        rows = next(iter(shapes.values()))[0]
        if key[0] is None:
            # request larger than the biggest bucket: chunk over it
            cap = self.spec.max_batch
            outs_per_chunk = []
            for lo in range(0, rows, cap):
                chunk = {n: a[lo:lo + cap] for n, a in inputs.items()}
                outs_per_chunk.append(self._predict_routed(chunk))
            return [_np.concatenate(parts, axis=0)
                    for parts in zip(*outs_per_chunk)]
        bucket_shapes = self.spec.bucket_input_shapes(key)
        with _flight.phase_span("serve_pad", cat="serving",
                                labels={"bucket": bucket_label(key)}):
            padded = {n: pad_to_shape(a, bucket_shapes[n])
                      for n, a in inputs.items()}
        if _metrics.ENABLED:
            _metrics.SERVE_PADDING_WASTE.set(
                self.spec.waste_fraction(key, shapes))
        outs = self._dispatch(key, padded)
        # valid-row mask: batch padding is dead rows at the tail; the
        # sequence axis (if any) is NOT sliced here — output seq layout
        # is model-defined (docs/inference.md).  The asarray below is
        # the request's ONE contractual device->host sync (serving is
        # host-in/host-out), not a hidden stall:
        with _flight.phase_span("serve_slice", cat="serving"):
            return [_np.asarray(o)[:rows] for o in outs]  # graft-lint: disable=host-sync

    def predict(self, *args, **kwargs) -> List[_np.ndarray]:
        """Run one request: positional args follow the symbol's input
        order, kwargs go by input name.  Returns host numpy outputs
        sliced to the request's valid rows."""
        served = self._served_names()
        if args:
            if kwargs or len(args) > len(served):
                raise MXNetError(
                    f"predict takes inputs {served} (got {len(args)} "
                    f"positional + {sorted(kwargs)})")
            kwargs = dict(zip(served, args))
        self._check_names(kwargs)  # before _as_host's dtype lookup
        t0 = time.perf_counter()
        inputs = {n: self._as_host(n, v) for n, v in kwargs.items()}
        self._check_request(inputs)
        fl = _flight.ENABLED
        trace_id = _flight.new_trace_id() if fl else None
        with _flight.trace_scope(trace_id) if fl \
                else _nullcontext():
            outs = self._predict_routed(inputs)
        dt = time.perf_counter() - t0
        if _metrics.ENABLED:
            _metrics.SERVE_REQUESTS.inc()
            _metrics.SERVE_LATENCY_SECONDS.observe(dt, exemplar=trace_id)
        if fl:
            _flight.note("serve_request", dt)
        return outs

    # C-predict-API-shaped alias (MXPredForward parity for callers
    # porting off `Predictor`)
    forward = predict

    # -- eviction / readmission (the multi-model HBM budget surface) ---------
    @property
    def resident(self) -> bool:
        """False after evict(): device weights (and every AOT bucket
        executable) are dropped; only the host param payload remains."""
        return self._resident

    def resident_bucket_ages(self) -> List[tuple]:
        """``[(key, last_used_monotonic)]`` for every RESIDENT bucket —
        the registry's LRU candidate list (stamped at precompile and at
        every dispatch)."""
        used = dict(self._bucket_used)
        return [(k, used.get(k, 0.0)) for k in list(self._compiled)]

    def bucket_cost_estimate(self, key: tuple) -> int:
        """Expected compiled peak HBM bytes of ``key`` — the admission
        question a budgeter asks BEFORE a precompile.  Exact for
        previously-compiled (evicted) buckets via their retained
        CompiledMemoryStats; a never-compiled bucket borrows the
        largest known peak of this model (0 when nothing is known yet —
        the ledger's hard budget stays the backstop)."""
        st = self._mem_stats.get(key)
        if st:
            return int(st.get("peak_bytes", 0))
        return int(max((v.get("peak_bytes", 0)
                        for v in dict(self._mem_stats).values()),
                       default=0))

    def host_payload_bytes(self) -> int:
        """Bytes the device weights would occupy on readmission (the
        host payload mirrors their shapes/dtypes exactly)."""
        p, a = self._host_payload
        return int(sum(_memory.nbytes_of(v) for d in (p, a)
                       for v in d.values()))

    def evict_bucket(self, key: tuple, blocking: bool = True) -> int:
        """Drop one bucket's AOT executable + zero placeholders (LRU
        bucket eviction).  The bucket's CompiledMemoryStats entry is
        kept as the readmission cost estimate.  Returns the estimated
        device bytes freed (compiled peak + tracked placeholders);
        idempotent.  ``blocking=False`` returns 0 when the compile
        lock is busy — a registry sweep must not stall every model's
        admission behind one model's in-flight XLA compile (a model
        mid-compile is not cold anyway)."""
        if not self._compile_lock.acquire(blocking=blocking):
            return 0
        try:
            return self._evict_bucket_locked(key)
        finally:
            self._compile_lock.release()

    def _evict_bucket_locked(self, key: tuple) -> int:
        if key not in self._compiled:
            return 0
        freed = int(self._mem_stats.get(key, {}).get("peak_bytes", 0))
        freed += sum(_memory.nbytes_of(a)
                     for a in self._extra.get(key, {}).values())
        del self._compiled[key]
        self._extra.pop(key, None)
        self._bucket_used.pop(key, None)
        if _metrics.ENABLED:
            # the per-bucket HBM gauge must not advertise an
            # executable that no longer exists
            _metrics.SERVE_BUCKET_HBM_BYTES.remove(
                bucket=bucket_label(key))
        return freed

    def evict(self, blocking: bool = True) -> int:
        """Full model eviction: every bucket executable, every zero
        placeholder, and the device weights are dropped — the host
        param payload stays, so ``readmit()`` is a reload + (cache-hit)
        recompile, never a restart.  Returns estimated device bytes
        freed.  In-flight dispatches that already read the weights pair
        finish on the old buffers (freed when they complete); new
        dispatches raise a typed ``ModelEvictedError``.
        ``blocking=False`` returns 0 when the compile lock is busy —
        a model mid-compile is not a cold victim, and a registry sweep
        holding its own lock must not stall every admission behind
        this model's XLA compile."""
        if not blocking:
            # probe-then-recurse: the RLock makes the blocking branch's
            # `with` nest inside this probe hold, so the busy check and
            # the eviction are one atomic acquisition
            if not self._compile_lock.acquire(blocking=False):
                return 0
            try:
                return self.evict()
            finally:
                self._compile_lock.release()
        with self._compile_lock:
            freed = 0
            # residency flips first: a dispatch racing this sees either
            # the full old pair (serves fine) or the empty pair + flag
            self._resident = False
            self._was_evicted = True
            for key in list(self._compiled):
                freed += self.evict_bucket(key)
            params, aux = self._weights
            freed += sum(_memory.nbytes_of(a) for d in (params, aux)
                         for a in d.values())
            self._weights = ({}, {})
            return freed

    # back-compat-friendly alias: "weights eviction" in the ladder docs
    evict_weights = evict

    def readmit(self) -> None:
        """Re-upload the host param payload to the device and mark the
        model servable again.  Bucket executables rebuild lazily at the
        next dispatch per key — a persistent-compile-cache hit when
        ``MXNET_COMPILE_CACHE_DIR`` is wired (counted as
        ``mxnet_serve_readmissions_total{kind="bucket"}``, never as a
        ``SERVE_COMPILES`` escape).  Idempotent."""
        with self._compile_lock:
            if self._resident:
                return
            if self._closed:
                raise MXNetError("predictor is closed")
            dev_j = self._ctx.jax_device()

            def _to_dev(v):
                return _memory.register(jax.device_put(v, dev_j),
                                        tag="serve_weights")

            host_p, host_a = self._host_payload
            # oom_guard: on a genuinely full device the upload fails
            # TYPED (DeviceMemoryError + post-mortem), never a raw
            # backend RESOURCE_EXHAUSTED — the ladder contract holds
            # at the readmission chokepoint too, and a registry can
            # map it to ModelUnavailable
            with _memory.oom_guard("serving.readmit"):
                self._weights = (
                    {k: _to_dev(v) for k, v in host_p.items()},
                    {k: _to_dev(v) for k, v in host_a.items()})
            self._resident = True
            was_evicted = self._was_evicted
        if was_evicted and _metrics.ENABLED:
            # a resident=False construction admitting for the first
            # time is not churn — only an evict->readmit cycle counts
            _metrics.SERVE_READMITS.inc(kind="model")

    def close(self) -> None:
        """Tear the predictor down completely: auto-reload stopped,
        device weights + executables + placeholders dropped, host
        payload released — every ledger-tagged byte (serve_weights
        device-side, serve_host_params host-side) returns to baseline
        once the caller drops its reference.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.stop_auto_reload()
        with self._compile_lock:
            self.evict()
            self._host_payload = ({}, {})
            self._mem_stats.clear()
            self._ever_compiled.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- checkpoint hot reload ----------------------------------------------
    @property
    def loaded_step(self):
        """Step of the last hot-reloaded checkpoint (None = construction
        params still serving)."""
        return getattr(self, "_loaded_step", None)

    def _as_checkpoint_manager(self, source):
        from ..checkpoint import CheckpointManager
        if isinstance(source, CheckpointManager):
            return source
        return CheckpointManager(str(source))

    def hot_reload(self, source, step=None) -> int:
        """Swap the served weights for those of the newest valid
        checkpoint under ``source`` (a checkpoint directory or
        ``CheckpointManager``) WITHOUT recompiling — shapes/dtypes must
        match the serving graph, so every AOT bucket executable keeps
        working.  Torn/corrupt checkpoints are skipped by the manager's
        validated restore; a checkpoint missing any served parameter
        raises and the old weights keep serving (no partial swap).
        Returns the loaded step."""
        from ..checkpoint import (ARG_PREFIX, AUX_PREFIX, PARAM_PREFIX)
        # chaos site: a raise here proves the old-weights-keep-serving
        # contract — auto-reload catches, counts, and keeps polling
        _fi_fire("serving.hot_reload")
        if not self._resident:
            # an evicted model has no served weights to swap; auto-reload
            # counts this as a failed poll and retries — the next poll
            # after readmit() picks the checkpoint up
            raise MXNetError(
                "hot_reload: model weights are evicted — readmit() first")
        mgr = self._as_checkpoint_manager(source)
        res = mgr.restore(step)
        if res is None:
            raise MXNetError(
                f"hot_reload: no valid checkpoint under {mgr.directory!r}")
        got_step, state = res

        # prefix-respecting lookup: a parameter loads from param:/arg:
        # entries only, aux state from aux: (falling back to param: —
        # gluon checkpoints carry BN running stats as Parameters).  An
        # arg: entry can never silently satisfy an aux name or vice
        # versa even when base names collide.
        new_host = ({}, {})

        def _lookup(name, prefixes, what, cur, host_out):
            for prefix in prefixes:
                if prefix + name in state:
                    arr = _np.asarray(state[prefix + name])
                    if tuple(arr.shape) != tuple(cur.shape):
                        raise MXNetError(
                            f"hot_reload: {what} '{name}' shape "
                            f"{arr.shape} != serving shape "
                            f"{tuple(cur.shape)}")
                    arr = arr.astype(cur.dtype, copy=False)
                    host_out[name] = _memory.register_host(
                        arr, tag="serve_host_params")
                    return _memory.register(jax.device_put(arr, dev_j),
                                            tag="serve_weights")
            raise MXNetError(
                f"hot_reload: checkpoint step {got_step} lacks served "
                f"{what} '{name}' — old weights keep serving")

        dev_j = self._ctx.jax_device()
        old_params, old_aux = self._weights
        new_params = {name: _lookup(name, (PARAM_PREFIX, ARG_PREFIX),
                                    "parameter", cur, new_host[0])
                      for name, cur in old_params.items()}
        new_aux = {name: _lookup(name, (AUX_PREFIX, PARAM_PREFIX),
                                 "aux state", cur, new_host[1])
                   for name, cur in old_aux.items()}
        # ONE reference assignment commits both dicts together:
        # in-flight _dispatch calls hold the old pair, new requests see
        # the new pair — never params of one step with aux of another.
        # Committed under the lifecycle lock: an evict/close racing
        # this swap must not be clobbered by a late reload commit
        with self._compile_lock:
            if not self._resident:
                raise MXNetError(
                    "hot_reload: model was evicted mid-reload — "
                    "readmit() first")
            self._weights = (new_params, new_aux)
            # the readmission source must follow the served weights, or
            # an evict/readmit cycle would resurrect pre-reload params
            self._host_payload = new_host
            self._loaded_step = got_step
        return got_step

    def start_auto_reload(self, source, interval_s: float = 30.0) -> None:
        """Poll ``source`` every ``interval_s`` and hot-reload whenever
        a newer valid checkpoint lands — the training-to-serving
        weight pipeline with no restarts.  Polling cost is one
        directory scan.

        Failure contract: a transiently missing/corrupt checkpoint dir
        or a failed weight swap is logged, counted in
        ``mxnet_serve_reload_failures_total``
        (``snapshot()["serving"]["reload_failures"]``), and the
        PREVIOUS weights keep serving — the poll thread never dies.
        ``_last_reload_ok`` tracks the last successful poll so
        ``ResilientServer.readyz()`` can flag hot-reload staleness."""
        import logging
        if getattr(self, "_reload_thread", None) is not None:
            raise MXNetError("auto-reload already running")
        mgr = self._as_checkpoint_manager(source)
        stop = threading.Event()
        self._reload_interval_s = float(interval_s)
        # a just-started poller is healthy by definition: staleness is
        # measured from here until the first (possibly failing) poll
        self._last_reload_ok = time.monotonic()
        self._last_reload_error: Optional[str] = None

        def _poll():
            while not stop.wait(interval_s):
                try:
                    newest = mgr.latest_step()
                    if newest is not None and newest != self.loaded_step:
                        self.hot_reload(mgr)
                    # a clean poll — including "nothing new" — refreshes
                    # the staleness clock
                    self._last_reload_ok = time.monotonic()
                    self._last_reload_error = None
                except Exception as e:  # noqa: BLE001 — keep serving
                    self._last_reload_error = f"{type(e).__name__}: {e}"
                    if _metrics.ENABLED:
                        _metrics.SERVE_RELOAD_FAILURES.inc()
                    logging.getLogger(__name__).warning(
                        "auto-reload failed (serving old weights): %s", e)

        self._reload_stop = stop
        self._reload_thread = threading.Thread(
            target=_poll, name="mxt-serve-reload", daemon=True)
        self._reload_thread.start()

    def stop_auto_reload(self) -> None:
        t = getattr(self, "_reload_thread", None)
        if t is None:
            return
        self._reload_stop.set()
        t.join(timeout=5)
        self._reload_thread = None
