"""Multi-model serving under an HBM budget: registry, LRU eviction,
restart-free readmission, typed degradation ladder.

One TPU serving process hosting N models has a resource problem the
single-model stack (ISSUEs 4/6/9) made legible but never solved: the
PR 9 ledger can SAY what each model's weights and bucket executables
cost, but nothing USED that — the k+1'th model was a hardware
``RESOURCE_EXHAUSTED``, not a policy decision.  This module is the
budgeter: the MXNet paper's multi-tenant KVStore-server story (arxiv
1512.01274) recast for single-process serving, with clipper-style
model-container management (arxiv 1612.03079) as the degradation
pattern.

``ModelRegistry`` hosts N ``BucketedPredictor``s, each behind its own
``ResilientServer`` (the PR 6 bounded queues / admission / shedding),
and enforces ``MXNET_HBM_BUDGET_MB``:

  * **admission asks first** — registering a model, readmitting an
    evicted one, or compiling a cold bucket checks the PR 9 ledger's
    tracked bytes + the per-bucket ``CompiledMemoryStats`` peaks
    against the budget (``memory.ensure_headroom``) BEFORE allocating;
  * **LRU eviction, buckets before models** — on a shortfall the
    registry drops cold bucket executables first (cheapest to rebuild:
    a persistent-compile-cache hit), then whole cold models' device
    weights (host param payload kept — readmission is a reload, never
    a restart).  Models with pending requests are never victims;
  * **typed degradation ladder** — ``full`` → ``buckets_evicted`` →
    ``weights_evicted`` → ``ModelUnavailable`` (with ``retry_after_s``)
    instead of an unhandled ``RESOURCE_EXHAUSTED``;
  * **OOM second chance** — a real (or ``memory.oom``-injected) OOM at
    a dispatch chokepoint triggers one arbiter eviction pass and ONE
    dispatch retry before failing callers (``ResilientServer``'s
    ``oom_retry`` hook);
  * **tenant→model routing** — ``bind(tenant, model)`` routes
    ``submit(tenant=...)`` through that model's existing bounded
    queues; per-model ``readyz()`` detail carries the degradation
    level;
  * **observability** — eviction/readmission run inside
    ``serve_evict``/``serve_readmit`` flight phases with ``mem=True``
    (the ledger timeline shows churn), and
    ``mxnet_serve_evictions_total{kind,model}`` /
    ``mxnet_serve_readmissions_total{kind}`` /
    ``mxnet_serve_resident_models`` / ``mxnet_serve_model_hbm_bytes``
    land in ``snapshot()["serving"]``;
  * **chaos-testable** — the ``serving.evict`` faultinject site fires
    once per victim, so tests drive deterministic churn
    (tests/test_registry.py, ``make chaos-serve``).

See docs/multi_model.md for the budget cost model and operations
guide.
"""
from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

import numpy as _np

from ..analysis import sanitizer as _san
from ..base import MXNetError, getenv
from ..faultinject import fire as _fi_fire
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from .predictor import BucketedPredictor
from .resilience import ResilientServer

log = logging.getLogger(__name__)

__all__ = ["ModelRegistry", "ModelUnavailable", "EVICT_POLICIES",
           "DEGRADATION_LADDER"]

EVICT_POLICIES = ("lru", "none")

#: the typed degradation ladder, least to most degraded — each model is
#: always at exactly one rung; requests only fail typed at the last
DEGRADATION_LADDER = ("full", "buckets_evicted", "weights_evicted",
                      "unavailable")


class ModelUnavailable(MXNetError):
    """The budget cannot host this model right now — every colder
    victim is already evicted (or busy, or eviction is disabled) and
    the bytes still don't fit.  ``retry_after_s`` estimates when churn
    frees capacity; an RPC front end maps it to ``Retry-After``.  This
    is the ladder's last rung: the request never reached the device, so
    there is nothing to OOM."""

    def __init__(self, message: str, retry_after_s: float = 0.5,
                 model: Optional[str] = None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.model = model


class _Entry:
    __slots__ = ("name", "predictor", "server", "last_used", "pinned")

    def __init__(self, name: str, predictor: BucketedPredictor,
                 server: ResilientServer, pinned: bool):
        self.name = name
        self.predictor = predictor
        self.server = server
        self.last_used = time.monotonic()
        self.pinned = pinned


class ModelRegistry:
    """N serving models in one process under one HBM budget.

    Parameters
    ----------
    budget_mb : float, optional
        Device-byte budget the registry schedules against (default:
        ``MXNET_HBM_BUDGET_MB``; 0 = no budget, everything admits).
        The ledger's soft-budget watchdog stays the hard backstop.
    max_models : int, optional
        Bound on registered models (default ``MXNET_SERVE_MAX_MODELS``,
        16).  Each model costs a scheduler thread pair, per-model metric
        series, and — resident — its weights; past the bound
        ``register`` raises.
    evict_policy : str, optional
        ``"lru"`` (default, ``MXNET_SERVE_EVICT_POLICY``) evicts cold
        buckets then cold models on budget pressure; ``"none"``
        disables eviction — over-budget admissions fail typed
        immediately (capacity planning mode).
    server_kwargs : dict, optional
        Forwarded to every model's ``ResilientServer`` (queue bounds,
        shed policy, watchdog thresholds).
    """

    def __init__(self, budget_mb: Optional[float] = None,
                 max_models: Optional[int] = None,
                 evict_policy: Optional[str] = None,
                 server_kwargs: Optional[dict] = None):
        if budget_mb is None:
            budget_mb = float(getenv("MXNET_HBM_BUDGET_MB", 0.0))
        self.budget_bytes = float(budget_mb) * 1048576.0
        self.max_models = int(getenv("MXNET_SERVE_MAX_MODELS", 16)) \
            if max_models is None else int(max_models)
        if self.max_models < 1:
            raise MXNetError("max_models must be >= 1")
        policy = evict_policy or getenv("MXNET_SERVE_EVICT_POLICY", "lru")
        if policy not in EVICT_POLICIES:
            raise MXNetError(f"evict_policy must be one of "
                             f"{EVICT_POLICIES}, got {policy!r}")
        self.evict_policy = policy
        self._server_kwargs = dict(server_kwargs or {})
        # RLock: admission calls ensure_headroom which re-enters the
        # registry through the arbiter (_make_room) on the same thread
        self._lock = _san.make_rlock("serving.registry")
        self._models: Dict[str, _Entry] = {}
        self._routes: Dict[str, str] = {}   # tenant -> model name
        # bytes promised to in-flight admissions (bucket compiles that
        # have not landed in the ledger yet), keyed (model, bucket)
        # with a holder refcount — released when the last admitting
        # request's future resolves
        self._reserved = 0.0
        self._rsv: Dict[tuple, list] = {}
        self._closed = False
        # the process-wide arbitration hook: OTHER subsystems asking
        # memory.ensure_headroom() get this registry's LRU evictor.
        # ONE bound-method object, pinned — every `self._arbit` access
        # creates a fresh bound method, so close()'s is-ours identity
        # check needs the exact installed object
        self._arbiter_fn = self._arbit
        self._prev_arbiter = _memory.set_budget_arbiter(self._arbiter_fn)

    # -- registration / routing ----------------------------------------------
    def register(self, name: str, symbol, params, input_shapes,
                 tenants=(), warmup: bool = True, pinned: bool = False,
                 server_kwargs: Optional[dict] = None,
                 **predictor_kwargs) -> ResilientServer:
        """Build + admit one model.  ``tenants`` pre-binds routing
        names; ``warmup=True`` AOT-compiles (and pre-executes) each
        bucket while the budget allows, leaving the rest cold;
        ``pinned=True`` exempts the model from eviction.  Past the
        budget even after eviction, the model is admitted
        **weights-evicted** (host payload only — it readmits on its
        first request if capacity has freed by then).  Raises on a
        duplicate name or a full registry."""
        with self._lock:
            if self._closed:
                raise MXNetError("ModelRegistry is closed")
            if name in self._models:
                raise MXNetError(f"model {name!r} already registered")
            if len(self._models) >= self.max_models:
                raise MXNetError(
                    f"registry full ({self.max_models} models, "
                    f"MXNET_SERVE_MAX_MODELS) — deregister one first")
        # build outside the lock: param loading can be slow, and the
        # arbiter must stay callable for other admissions.  The
        # predictor constructs resident=False — its host payload is
        # the ONLY copy (no duplicate normalization pass here) and NO
        # device bytes allocate until the budget has answered, so an
        # over-budget registration cannot transiently blow the very
        # budget (or device) it is being checked against
        pred = BucketedPredictor(symbol, params, input_shapes,
                                 resident=False, **predictor_kwargs)
        est = pred.host_payload_bytes()
        # check AND upload under the registry lock: two concurrent
        # admissions must not both be granted the same headroom (the
        # submit()-path TOCTOU, closed the same way).  The upload is a
        # device_put per array — bounded, unlike an XLA compile
        with self._lock:
            fits = self._ensure_fits(est, exclude=name,
                                     why=f"register:{name}")
            if fits:
                pred.readmit()  # first admission: not counted as churn
        kw = dict(self._server_kwargs)
        kw.update(server_kwargs or {})
        server = ResilientServer(
            pred,
            extra_ready=lambda n=name, p=pred: ({}, {
                "model": n, "degradation": self._degradation(p)}),
            oom_retry=lambda e, n=name: self._on_oom(n, e),
            **kw)
        entry = _Entry(name, pred, server, pinned)
        with self._lock:
            # re-check: the build above ran unlocked, so a concurrent
            # register of the same name (or a close()) may have won —
            # a silent overwrite would orphan the loser's scheduler
            # threads and device weights forever
            lost = self._closed or name in self._models \
                or len(self._models) >= self.max_models
            if not lost:
                self._models[name] = entry
                for t in tenants:
                    self._routes[str(t)] = name
        if lost:
            server.close()
            pred.close()
            raise MXNetError(
                f"model {name!r} lost a registration race (duplicate "
                f"name, closed registry, or registry full)")
        if not fits:
            # over budget even after eviction: admitted at the
            # weights_evicted rung (it readmits on its first request
            # once capacity frees)
            log.warning("model %r does not fit the HBM budget at "
                        "registration — admitted weights-evicted", name)
        elif warmup:
            self.warmup(name)
        self._refresh_gauges()
        return server

    def warmup(self, name: str, keys=None) -> int:
        """Budget-gated warmup: compile + pre-execute buckets for
        ``name`` until the budget says stop (remaining buckets stay
        cold and compile lazily, budget permitting, at first dispatch).
        Returns the number of buckets made resident."""
        e = self._entry(name)
        done = 0
        for key in (keys if keys is not None
                    else e.predictor.spec.all_keys()):
            key = tuple(key)
            if key in e.predictor._compiled:
                done += 1
                continue
            # grant + reserve under the lock, compile OUTSIDE it: the
            # reservation keeps concurrent admissions honest about the
            # promised bytes without stalling them behind this XLA
            # compile (the submit()-path discipline)
            rk = (e.name, key)
            with self._lock:
                est = self._bucket_increment(e, key)
                if not self._ensure_fits(est, exclude=name,
                                         why=f"warmup:{name}"):
                    log.warning("warmup of %r stopped by the HBM "
                                "budget after %d bucket(s) — the rest "
                                "stay cold", name, done)
                    return done
                ent = self._rsv.get(rk)
                if ent is None:
                    ent = self._rsv[rk] = [float(est), 0]
                    self._reserved += ent[0]
                ent[1] += 1
            try:
                e.server.warmup(keys=[key])
            finally:
                self._release_key(rk)
            done += 1
        return done

    def bind(self, tenant: str, model: str) -> None:
        """Route ``tenant``'s requests to ``model`` (``"*"`` = default
        route for unbound tenants)."""
        with self._lock:
            self._entry(model)
            self._routes[str(tenant)] = str(model)

    def deregister(self, name: str) -> None:
        """Remove + tear down one model (server closed, predictor
        closed, routes dropped, ledger bytes returned)."""
        with self._lock:
            e = self._models.pop(name, None)
            if e is None:
                return
            for t in [t for t, m in self._routes.items() if m == name]:
                del self._routes[t]
        e.server.close()
        e.predictor.close()
        if _metrics.ENABLED:
            _metrics.SERVE_MODEL_HBM_BYTES.remove(model=name)
        self._refresh_gauges()

    def models(self) -> List[str]:
        with self._lock:
            return list(self._models)

    def _entry(self, name: str) -> _Entry:
        with self._lock:
            e = self._models.get(name)
        if e is None:
            raise MXNetError(f"unknown model {name!r}; registered: "
                             f"{sorted(self._models)}")
        return e

    def _resolve(self, model: Optional[str], tenant: str) -> _Entry:
        if model is None:
            with self._lock:
                model = self._routes.get(tenant) or self._routes.get("*")
            if model is None:
                raise MXNetError(
                    f"no model routed for tenant {tenant!r} (bind() a "
                    f"route or pass model=)")
        return self._entry(model)

    # -- request path --------------------------------------------------------
    def submit(self, model: Optional[str] = None, tenant: str = "default",
               deadline_ms: Optional[float] = None, priority: int = 0,
               **inputs):
        """Route one request to its model's ``ResilientServer`` queue.

        The budget negotiation happens HERE, on the caller's thread,
        before the request is admitted: a weights-evicted model is
        readmitted (LRU-evicting colder victims to make room) and a
        cold target bucket's compiled peak is reserved.  When the bytes
        cannot be freed — every victim hotter or busy — the request
        fails with a typed ``ModelUnavailable`` carrying
        ``retry_after_s``, and is never admitted (goodput counts only
        admitted work).  Everything after admission is the PR 6
        contract: bounded queues, deadline shedding, typed errors."""
        e = self._resolve(model, tenant)
        e.last_used = time.monotonic()
        key = None
        try:
            # route outside the lock (pure shape math; reading .shape
            # never syncs a device-resident NDArray the way np.asarray
            # would).  A malformed request leaves key=None and fails
            # typed in server.submit's returned future
            shapes = {}
            for n, v in inputs.items():
                s = getattr(v, "shape", None)
                shapes[n] = tuple(s) if s is not None \
                    else _np.asarray(v).shape
            key = e.predictor.spec.route(shapes)
            if key[0] is None:
                key = None  # oversize: chunks over existing buckets
        except Exception:  # noqa: BLE001 — malformed requests
            key = None
        rsv_key = None
        with self._lock:
            # the budget question is answered UNDER the lock, against
            # residency as it is NOW — a concurrent submit's eviction
            # sweep may have changed it since routing above, and a
            # readmit decided on stale residency would upload weights
            # no headroom was ever granted for
            need = 0 if e.predictor.resident \
                else e.predictor.host_payload_bytes()
            cold_bucket = key is not None \
                and key not in e.predictor._compiled
            # reservations are per (model, bucket), refcounted per
            # request: a burst of N submits to one cold bucket must
            # charge the budget ONE compile, not N (followers ride the
            # first reservation, which _reserved already counts)
            bucket_est = 0
            if cold_bucket and (e.name, key) not in self._rsv:
                bucket_est = self._bucket_increment(e, key)
            if need + bucket_est > 0:
                if not self._ensure_fits(need + bucket_est,
                                         exclude=e.name,
                                         why=f"admit:{e.name}"):
                    retry = self._retry_after()
                    raise ModelUnavailable(
                        f"model {e.name!r} needs ~{need + bucket_est} "
                        f"device bytes the HBM budget cannot free "
                        f"(every victim is hotter or busy); retry "
                        f"after ~{retry:.2f}s", retry_after_s=retry,
                        model=e.name)
            if not e.predictor.resident:
                try:
                    self._readmit(e)
                except _memory.DeviceMemoryError as ex:
                    # budget said yes but the device itself is full
                    # (budget off, or untracked pressure): stay on the
                    # ladder — the caller gets retry-after, the
                    # post-mortem dump has already been triggered
                    retry = self._retry_after()
                    raise ModelUnavailable(
                        f"model {e.name!r} readmission hit device "
                        f"memory exhaustion; retry after "
                        f"~{retry:.2f}s", retry_after_s=retry,
                        model=e.name) from ex
            if cold_bucket:
                rsv_key = (e.name, key)
                ent = self._rsv.get(rsv_key)
                if ent is None:
                    ent = self._rsv[rsv_key] = [float(bucket_est), 0]
                    self._reserved += ent[0]
                ent[1] += 1
        fut = None
        try:
            fut = e.server.submit(tenant=tenant, deadline_ms=deadline_ms,
                                  priority=priority, **inputs)
        finally:
            # a shed (Overloaded/closed/dead raise) never attaches the
            # done-callback — release the reservation here or headroom
            # leaks away one shed at a time
            if fut is None and rsv_key is not None:
                self._release_key(rsv_key)
        if rsv_key is not None:
            fut.add_done_callback(
                lambda _f, k=rsv_key: self._release_key(k))
        return fut

    def predict(self, model: Optional[str] = None, tenant: str = "default",
                deadline_ms: Optional[float] = None, priority: int = 0,
                **inputs):
        """Blocking ``submit`` — raises the typed ladder errors
        (``ModelUnavailable`` / ``Overloaded`` / ``DeadlineExceeded``)
        in the caller's thread."""
        return self.submit(model=model, tenant=tenant,
                           deadline_ms=deadline_ms, priority=priority,
                           **inputs).result()

    def _release_key(self, rk: tuple) -> None:
        """Drop one request's hold on a (model, bucket) reservation;
        the reserved bytes return to headroom when the LAST holder's
        future resolves (by then the compile — if it happened — is in
        _mem_stats and counted by _committed_bytes instead)."""
        with self._lock:
            ent = self._rsv.get(rk)
            if ent is None:
                return
            ent[1] -= 1
            if ent[1] <= 0:
                self._reserved = max(0.0, self._reserved - ent[0])
                del self._rsv[rk]

    # -- the budget scheduler ------------------------------------------------
    # Cost model (docs/multi_model.md): a model's budget footprint is
    # its tracked ledger bytes (weights + placeholders — the PR 9
    # weakref ledger is ground truth) PLUS its largest resident bucket
    # executable's compiled peak (CompiledMemoryStats — the transient
    # working set one dispatch needs; one dispatch at a time per
    # model).  Backends whose PJRT reports no compiled stats (older
    # CPU) degrade to the ledger-only view: weights still budget,
    # bucket churn frees only its tracked placeholders.
    def _committed_bytes(self) -> float:
        """Sum over models of the largest RESIDENT bucket's compiled
        peak — dispatch working set the budget must hold in reserve."""
        total = 0.0
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            try:
                total += e.predictor.memory_stats()["peak_bytes_max"]
            except Exception:  # noqa: BLE001 — stats are best-effort
                pass
        return total

    def _headroom(self) -> float:
        h = _memory.headroom_bytes(
            self.budget_bytes if self.budget_bytes > 0 else None)
        if h == float("inf"):
            return h
        return h - self._reserved - self._committed_bytes()

    def _ensure_fits(self, nbytes: float, exclude: Optional[str],
                     why: str) -> bool:
        """True when ``nbytes`` more device bytes fit the budget,
        LRU-evicting cold buckets then cold models to make it so."""
        if self.budget_bytes <= 0 or nbytes <= 0:
            return True
        h = self._headroom()
        if h >= nbytes:
            return True
        self._make_room(nbytes - h, exclude=exclude, why=why)
        return self._headroom() >= nbytes

    def _bucket_increment(self, e: "_Entry", key: tuple) -> int:
        """Budget increment of making bucket ``key`` resident: its
        compiled-peak estimate beyond the model's current largest
        resident bucket (the committed term counts only the max)."""
        est = e.predictor.bucket_cost_estimate(key)
        try:
            cur = e.predictor.memory_stats()["peak_bytes_max"]
        except Exception:  # noqa: BLE001
            cur = 0
        return max(0, int(est) - int(cur))

    def _arbit(self, deficit: float, why: str) -> float:
        """The ``memory.set_budget_arbiter`` hook: any subsystem asking
        ``memory.ensure_headroom`` for device bytes gets this
        registry's LRU evictor."""
        return self._make_room(deficit, exclude=None, why=why)

    def _make_room(self, deficit: float, exclude: Optional[str],
                   why: str) -> float:
        """Free ~``deficit`` budget bytes: phase 1 evicts cold bucket
        executables (oldest last-use first, across models), phase 2
        evicts whole cold models' weights (LRU, idle only).  Progress
        is MEASURED — tracked ledger delta + committed compiled-peak
        delta — not trusted from estimates, so a backend with no
        compiled stats still converges (bucket churn frees little
        there; model eviction does the work).  The requesting model
        (``exclude``), pinned models, and models with pending requests
        are never weight-eviction victims.  Returns bytes freed."""
        if self.evict_policy != "lru":
            return 0.0
        with self._lock:
            t0 = _memory.tracked_bytes()
            c0 = self._committed_bytes()

            def _freed():
                return ((t0 - _memory.tracked_bytes())
                        + (c0 - self._committed_bytes()))

            # phase 0: decode KV pages — the CHEAPEST victims in the
            # ladder (an evicted sequence retries with a typed
            # retry-after; an evicted bucket recompiles, an evicted
            # model re-uploads weights).  Lazy import: decode never
            # imports the registry, so no cycle — and a process with
            # no engine alive pays one cached-import check
            if _freed() < deficit:
                try:
                    from . import decode as _decode
                    _decode.reclaim_kv_pages(deficit - _freed(),
                                             why=why)
                except Exception as e:  # noqa: BLE001 — best-effort
                    log.debug("decode KV reclaim skipped: %s", str(e))

            # phase 1: cold buckets — cheapest churn (a readmission is
            # a persistent-cache hit, the weights never move)
            cands = []
            for e in self._models.values():
                if e.name == exclude or e.pinned:
                    continue
                for key, used in e.predictor.resident_bucket_ages():
                    cands.append((used, e, key))
            for _used, e, key in sorted(cands, key=lambda c: c[0]):
                if _freed() >= deficit:
                    break
                self._evict_bucket(e, key, why=why, blocking=False)
            if _freed() < deficit:
                # phase 2: cold models, least recently used first
                victims = sorted(
                    (e for e in self._models.values()
                     if e.name != exclude and not e.pinned
                     and e.predictor.resident),
                    key=lambda e: e.last_used)
                for e in victims:
                    if _freed() >= deficit:
                        break
                    if e.server.pending():
                        continue  # owes queued/in-flight requests
                    self._evict_model(e, why=why)
            return max(0.0, _freed())

    def _evict_bucket(self, e: _Entry, key: tuple, why: str,
                      blocking: bool = True) -> float:
        try:
            # chaos site: fired BEFORE any state is dropped, so a raise
            # rule models a failed eviction — the victim stays fully
            # resident and the budgeter moves to the next candidate.
            # blocking=False skips victims whose compile lock is busy
            # (an in-flight compile means the bucket is not cold, and
            # waiting would stall every admission behind one XLA
            # compile while the registry lock is held)
            _fi_fire("serving.evict", model=e.name, kind="bucket",
                     why=why)
            with _flight.phase_span("serve_evict", cat="serving",
                                    mem=True, labels={"model": e.name}):
                freed = e.predictor.evict_bucket(key, blocking=blocking)
        except Exception as ex:  # noqa: BLE001 — skip this victim
            # str(ex): a buffered LogRecord holding the exception
            # object would pin its traceback frames (and any device
            # buffers they reference)
            log.warning("bucket eviction of %r failed (%s); skipping: "
                        "%s", e.name, why, str(ex))
            return 0.0
        if freed and _metrics.ENABLED:
            _metrics.SERVE_EVICTIONS.inc(kind="bucket", model=e.name)
        if freed and _journal.ENABLED:
            _journal.emit("serve_degradation", model=e.name,
                          kind="bucket", why=why,
                          level=self._degradation(e.predictor))
        return float(freed)

    def _evict_model(self, e: _Entry, why: str) -> float:
        try:
            _fi_fire("serving.evict", model=e.name, kind="model",
                     why=why)
            with _flight.phase_span("serve_evict", cat="serving",
                                    mem=True, labels={"model": e.name}):
                # non-blocking for the same reason as bucket sweeps: a
                # victim mid-compile (registry warmup on another
                # thread) is not cold, and waiting here would stall
                # every admission behind its XLA compile while the
                # registry lock is held
                freed = e.predictor.evict(blocking=False)
        except Exception as ex:  # noqa: BLE001 — skip this victim
            log.warning("model eviction of %r failed (%s); skipping: %s",
                        e.name, why, str(ex))
            return 0.0
        if freed == 0 and e.predictor.resident:
            return 0.0  # compile-lock busy: victim skipped, not evicted
        if _metrics.ENABLED:
            _metrics.SERVE_EVICTIONS.inc(kind="model", model=e.name)
        if _journal.ENABLED:
            _journal.emit("serve_degradation", model=e.name,
                          kind="model", why=why,
                          level=self._degradation(e.predictor))
        self._refresh_gauges()
        return float(freed)

    def _readmit(self, e: _Entry) -> None:
        with _flight.phase_span("serve_readmit", cat="serving",
                                mem=True, labels={"model": e.name}):
            e.predictor.readmit()
        if _journal.ENABLED:
            _journal.emit("serve_degradation", model=e.name,
                          kind="readmit",
                          level=self._degradation(e.predictor))
        self._refresh_gauges()

    def _on_oom(self, name: str, exc) -> bool:
        """``ResilientServer``'s OOM second chance: the device is
        GENUINELY over — cold-bucket churn is too small to matter, so
        evict one whole LRU idle model (beyond the OOMing one) if any
        exists, then grant ONE dispatch retry either way (cheap,
        bounded: a second OOM propagates typed — and transient
        pressure, e.g. another model's in-flight dispatch peak, may
        have passed even when nothing was evictable).  False only when
        eviction policy is off."""
        if self.evict_policy != "lru":
            return False
        with self._lock:
            victims = sorted(
                (e for e in self._models.values()
                 if e.name != name and not e.pinned
                 and e.predictor.resident and not e.server.pending()),
                key=lambda e: e.last_used)
            for e in victims:
                if self._evict_model(e, why=f"oom:{name}") > 0:
                    break
        return True

    def _retry_after(self) -> float:
        """When might churn free capacity?  The soonest-draining busy
        victim's estimated wait, floored at 50ms."""
        with self._lock:
            ests = [e.server._estimate_wait_s(
                e.server._total_rows() or 1)
                for e in self._models.values() if e.server.pending()]
        return max(0.05, min(ests)) if ests else 0.5

    # -- introspection -------------------------------------------------------
    def degradation(self, name: str) -> str:
        """The model's current rung on ``DEGRADATION_LADDER``."""
        return self._degradation(self._entry(name).predictor)

    @staticmethod
    def _degradation(pred: BucketedPredictor) -> str:
        """Rung from a held predictor — readyz()/stats()/extra_ready
        use this so a concurrent deregister cannot turn the health
        endpoint into an unknown-model raise mid-churn."""
        if not pred.resident:
            return "weights_evicted"
        # list() snapshots: a dispatch thread's first-time compile
        # mutates _ever_compiled/_compiled while a scrape thread reads
        # here (the concurrent-iteration class PR 13 fixed elsewhere)
        compiled = dict(pred._compiled)
        if any(k not in compiled for k in list(pred._ever_compiled)):
            return "buckets_evicted"
        return "full"

    def _refresh_gauges(self) -> None:
        if not _metrics.ENABLED:
            return
        with self._lock:
            entries = list(self._models.values())
        resident = 0
        items = []
        for e in entries:
            try:
                ms = e.predictor.memory_stats()
            except Exception:  # noqa: BLE001 — gauges are best-effort
                continue
            if e.predictor.resident:
                resident += 1
                items.append(({"model": e.name}, ms["weights_bytes"]))
            else:
                items.append(({"model": e.name}, 0))
        _metrics.SERVE_RESIDENT_MODELS.set(float(resident))
        _metrics.SERVE_MODEL_HBM_BYTES.replace_children(items)

    def readyz(self) -> dict:
        """Aggregated traffic-worthiness: the registry is ready when
        every model's scheduler is healthy and at least one model can
        take traffic; per-model blocks carry each server's full
        ``readyz`` plus the degradation rung (an evicted model is NOT
        unready — it readmits on demand; only a dead scheduler is)."""
        self._refresh_gauges()
        models = {}
        with self._lock:
            entries = list(self._models.items())
        healthy, any_ready = True, False
        for name, e in entries:
            rz = e.server.readyz()
            hz = e.server.healthz()
            models[name] = {
                "ready": rz["ready"],
                "degradation": self._degradation(e.predictor),
                "reasons": rz["reasons"],
                "detail": rz["detail"],
                "healthy": hz["ok"],
            }
            healthy = healthy and hz["ok"]
            any_ready = any_ready or rz["ready"]
        with self._lock:
            reserved = self._reserved
        return {
            "ready": bool(healthy and (any_ready or not entries)),
            "models": models,
            "budget": {
                "budget_bytes": self.budget_bytes,
                "tracked_bytes": int(_memory.tracked_bytes()),
                "reserved_bytes": int(reserved),
                "headroom_bytes": (None if self.budget_bytes <= 0
                                   else int(self._headroom())),
                "evict_policy": self.evict_policy,
            },
        }

    def stats(self) -> dict:
        with self._lock:
            entries = list(self._models.items())
            routes = dict(self._routes)
        return {
            "models": {n: {"degradation": self._degradation(e.predictor),
                           "resident": e.predictor.resident,
                           "resident_buckets": e.predictor.num_compiled,
                           "last_used": e.last_used,
                           "pinned": e.pinned,
                           "server": e.server.stats()}
                       for n, e in entries},
            "routes": routes,
            "budget_bytes": self.budget_bytes,
            "reserved_bytes": self._reserved,
            "evict_policy": self.evict_policy,
        }

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Tear every model down and uninstall the budget arbiter.
        After close + the caller dropping its references, every
        serve_weights / serve_host_params ledger byte is back to
        baseline (the registry leak gate pins this)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            names = list(self._models)
        # restore whatever arbiter we displaced (usually None) — but
        # only if ours is still installed: closing an older registry
        # must not rip out (or shadow with a dead evictor) the arbiter
        # a NEWER registry has since installed.  And never reinstall a
        # CLOSED registry's evictor (out-of-order close: A then B
        # would otherwise resurrect closed A's no-op arbiter and pin
        # its object alive)
        prev = self._prev_arbiter
        owner = getattr(prev, "__self__", None)
        if isinstance(owner, ModelRegistry) and owner._closed:
            prev = None
        cur = _memory.set_budget_arbiter(prev)
        if cur is not self._arbiter_fn:
            _memory.set_budget_arbiter(cur)
        for n in names:
            self.deregister(n)
        if _metrics.ENABLED:
            _metrics.SERVE_RESIDENT_MODELS.set(0.0)
            _metrics.SERVE_MODEL_HBM_BYTES.replace_children([])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
