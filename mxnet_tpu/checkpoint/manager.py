"""CheckpointManager: non-blocking snapshots, retention, auto-resume.

The three legs production training stands on (TensorFlow, arxiv
1605.08695 §4.4 — and the north star's "survive anything" bar):

  * **async snapshots** — ``save(step, state)`` copies every tensor to
    host eagerly (training may donate/mutate its buffers immediately)
    and hands serialization + IO to one background writer thread, so
    the step critical path pays only the memcpy.  ``wait()`` is the
    barrier; ``MXNET_CHECKPOINT_ASYNC=0`` (or ``async_save=False``)
    keeps everything on the caller thread.
  * **atomic, validated layout** — see ``layout.py``: tmp + rename
    commit, per-entry CRC32, size-checked shards.  Restore walks steps
    newest-first and a torn/corrupt checkpoint is skipped (counted in
    ``mxnet_checkpoint_failures_total``), never loaded.
  * **retention + discovery** — ``max_to_keep`` GC with ``keep_period``
    pinning; ``latest_step()`` / ``all_steps()`` ignore invalid dirs.

Transient IO errors retry with exponential backoff
(``MXNET_CHECKPOINT_RETRIES``, default 3 retries); tests inject faults
through ``fault_hook``.
"""
from __future__ import annotations

import atexit
import itertools
import logging
import os
import shutil
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis import sanitizer as _san
from ..base import MXNetError, getenv
from ..faultinject import fire as _fi_fire
from ..observability import flight as _flight
from ..observability import journal as _journal
from ..observability import memory as _memory
from ..observability import metrics as _metrics
from . import layout as _layout
from .layout import CheckpointInvalidError

log = logging.getLogger(__name__)


def _corrupt_step_dir(path: str) -> None:
    """Chaos helper for the ``checkpoint.io`` corrupt rule: flip the
    last byte of the first shard in a COMMITTED checkpoint dir —
    exactly the bit-rot/torn-replication damage the CRC-validated
    restore exists to catch (quick_validate still passes, sizes are
    unchanged; the load must reject it)."""
    try:
        names = sorted(n for n in os.listdir(path) if n.endswith(".npz"))
    except OSError:
        return
    if not names:
        return
    fp = os.path.join(path, names[0])
    # flip a byte mid-file: that lands in array payload (CRC mismatch)
    # or a zip member header (shard unreadable) — either way the
    # validated restore must reject the checkpoint.  A trailing-byte
    # flip would land in the zip end-of-central-directory slack, which
    # readers tolerate.
    size = os.path.getsize(fp)
    with open(fp, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


class CheckpointError(MXNetError):
    """A checkpoint write failed after exhausting retries."""


class CheckpointManager:
    """Manage a directory of atomic, validated ``step_N`` checkpoints.

    Parameters
    ----------
    directory : str
        Checkpoint root; created on first save.
    max_to_keep : int, optional
        GC all but the newest N valid checkpoints (None keeps all).
    keep_period : int, optional
        Steps divisible by this are pinned — never GC'd — regardless
        of ``max_to_keep`` (the "one per day forever" pattern).
    async_save : bool, optional
        Default ``MXNET_CHECKPOINT_ASYNC`` (on).  Off = every save
        completes before ``save()`` returns.
    retries : int, optional
        Transient-IO retries per save, default
        ``MXNET_CHECKPOINT_RETRIES`` (3).
    backoff_s : float, optional
        First retry delay, doubling each attempt; default
        ``MXNET_CHECKPOINT_RETRY_BACKOFF_S`` (0.05).
    fault_hook : callable, optional
        ``fault_hook(step, attempt)`` runs at the top of every write
        attempt — tests raise from it to exercise the retry path.
    max_pending : int, optional
        Backpressure bound on queued async saves (default
        ``MXNET_CHECKPOINT_MAX_PENDING``, 2).  Each queued save pins a
        full host-RAM snapshot of the state; when storage falls behind,
        ``save()`` blocks until a slot frees instead of growing the
        queue until the process OOMs.
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None,
                 keep_period: Optional[int] = None,
                 async_save: Optional[bool] = None,
                 retries: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 fault_hook: Optional[Callable[[int, int], None]] = None,
                 max_pending: Optional[int] = None):
        self.directory = str(directory)
        self.max_to_keep = None if max_to_keep is None else int(max_to_keep)
        self.keep_period = None if keep_period is None else int(keep_period)
        if self.max_to_keep is not None and self.max_to_keep < 1:
            raise MXNetError("max_to_keep must be >= 1 (or None)")
        if self.keep_period is not None and self.keep_period < 1:
            raise MXNetError("keep_period must be >= 1 (or None)")
        self._async = bool(getenv("MXNET_CHECKPOINT_ASYNC", True)) \
            if async_save is None else bool(async_save)
        self.retries = int(getenv("MXNET_CHECKPOINT_RETRIES", 3)) \
            if retries is None else int(retries)
        self.backoff_s = float(getenv("MXNET_CHECKPOINT_RETRY_BACKOFF_S",
                                      0.05)) if backoff_s is None \
            else float(backoff_s)
        self.fault_hook = fault_hook
        self.max_pending = int(getenv("MXNET_CHECKPOINT_MAX_PENDING", 2)) \
            if max_pending is None else int(max_pending)
        if self.max_pending < 1:
            raise MXNetError("max_pending must be >= 1")
        # lock-FREE token source (itertools.count is GIL-atomic).  It
        # used to ride self._lock, but that acquisition happened while
        # the writer held _write_lock (write→queue edge) while the
        # SIGTERM emergency save acquires _write_lock while the main
        # thread may hold _lock (queue→write edge) — an ABBA deadlock
        # the MXNET_SANITIZE=1 lock-order graph flags and
        # tests/test_analysis.py pins.  With the counter lock-free the
        # writer never blocks on _lock while holding _write_lock.
        self._seq = itertools.count(1)
        self._last_saved_step: Optional[int] = None
        # serializes actual writes: a block=True save (preemption hook)
        # may run on the caller thread concurrently with the worker —
        # without this, the worker's GC could sweep the blocking save's
        # in-flight .tmp dir.  RLock: the SIGTERM handler runs on the
        # main thread and may interrupt a synchronous save there; a
        # plain lock would deadlock the emergency save on the frame
        # below it
        self._write_lock = _san.make_rlock("checkpoint.manager.write")
        # queue/accounting condition — REENTRANT for the same SIGTERM
        # reason as _write_lock: the emergency save path re-enters
        # _lock's critical sections (save → _raise_pending_error /
        # _next_seq / wait) and the signal can land while the main
        # thread is INSIDE one of them (save()'s backpressure wait,
        # wait()'s drain loop).  With a plain Condition the handler
        # deadlocks the process during its SIGTERM grace window — the
        # ordering hazard the MXNET_SANITIZE=1 lock sanitizer flags
        # (tests/test_analysis.py pins it); Condition.wait still fully
        # releases the RLock recursion via _release_save
        self._lock = _san.make_condition("checkpoint.manager.queue",
                                         reentrant=True)
        self._queue: List[tuple] = []
        self._pending = 0
        self._errors: List[BaseException] = []
        self._worker: Optional[threading.Thread] = None
        self._closed = False

    # -- save ----------------------------------------------------------------
    def save(self, step: int, state: Dict, meta: Optional[dict] = None,
             signatures: Optional[dict] = None, block: bool = False) -> None:
        """Snapshot ``state`` (device→host, eager) and persist it as
        checkpoint ``step``.  Returns as soon as the snapshot is taken
        unless sync mode / ``block=True``.  A previously failed async
        save raises here (and from ``wait()``) — failures are never
        silent."""
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        self._raise_pending_error()
        step = int(step)
        t0 = time.perf_counter()
        # the caller-visible blocking phase: snapshot + (async) queue
        # admission, or the whole write in sync mode — the flight span
        # answers "what stole time from MY step", CHECKPOINT_SAVE_SECONDS
        # answers "how long did the write take"
        with _flight.phase_span("checkpoint_block", cat="checkpoint",
                                step=step, mem=True):
            snap = _layout.snapshot_state(state)
            if _memory.ENABLED:
                # host-side ledger twin: each queued async save pins a
                # full host-RAM snapshot until the writer commits it —
                # exactly the host hog worth attributing.  Registered
                # per payload array; the weakrefs die when the job is
                # dropped after commit, so a drained queue reads zero.
                for _name, (kind, payload) in snap.items():
                    if kind == "array":
                        _memory.register_host(payload,
                                              tag="checkpoint_host")
            job = (step, snap, dict(meta or {}), dict(signatures or {}),
                   t0)
            if self._async and not block:
                with self._lock:
                    self._ensure_worker()
                    # backpressure: degrade toward synchronous when
                    # storage can't keep up, never queue unboundedly
                    # (each job pins a full host snapshot)
                    while self._pending >= self.max_pending:
                        self._lock.wait()
                    self._queue.append(job)
                    self._pending += 1
                    self._lock.notify_all()
            else:
                self._run_job(job)
                self._raise_pending_error()
        if _metrics.ENABLED:
            _metrics.CHECKPOINT_SAVE_BLOCKED_SECONDS.observe(
                time.perf_counter() - t0)

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._worker = threading.Thread(
            target=self._worker_loop, name="mxt-checkpoint-writer",
            daemon=True)
        self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._lock.wait()
                if not self._queue and self._closed:
                    return
                job = self._queue.pop(0)
            try:
                self._run_job(job)
            finally:
                with self._lock:
                    self._pending -= 1
                    self._lock.notify_all()

    def _run_job(self, job: tuple) -> None:
        """Failures are NEVER silent: any exception — retried IO or a
        serialization bug — either raises (sync) or lands in _errors
        for wait()/the next save() to re-raise (async)."""
        step = job[0]
        try:
            with self._write_lock:
                self._run_job_locked(job)
        except Exception as e:  # noqa: BLE001 — see docstring
            if _metrics.ENABLED:
                # retries-exhausted CheckpointErrors chain the last IO
                # error — count the root cause, not the wrapper
                root = e.__cause__ if isinstance(e, CheckpointError) \
                    and e.__cause__ is not None else e
                _metrics.CHECKPOINT_FAILURES.inc(
                    stage="save", reason=type(root).__name__)
            err = e if isinstance(e, CheckpointError) else CheckpointError(
                f"checkpoint step {step} failed: {e}")
            if self._async:
                log.error("%s", err)
                with self._lock:
                    self._errors.append(err)
                return
            raise err from e

    def _run_job_locked(self, job: tuple) -> None:
        step, snap, meta, signatures, t0 = job
        with _flight.phase_span("checkpoint_write", cat="checkpoint",
                                step=step):
            self._run_attempts(step, snap, meta, signatures, t0)

    def _run_attempts(self, step, snap, meta, signatures, t0) -> None:
        attempts = self.retries + 1
        delay = self.backoff_s
        for attempt in range(attempts):
            try:
                if self.fault_hook is not None:
                    self.fault_hook(step, attempt)
                # process-wide chaos site generalizing the per-manager
                # fault_hook: raise OSError to exercise the retry path,
                # the default InjectedFault to exhaust it into a typed
                # CheckpointError; delay models slow storage
                _fi_fire("checkpoint.io", step=step, attempt=attempt)
                written = _layout.write_checkpoint_dir(
                    self.directory, step, snap, meta=meta,
                    signatures=signatures,
                    tmp_token=f"{os.getpid()}-{self._next_seq()}")
                # corrupt rules fire AFTER the commit (only= keeps the
                # raise/delay rules above from double-firing): the next
                # restore must skip this checkpoint via CRC validation
                _fi_fire("checkpoint.io", only="corrupt",
                         corrupt=lambda: _corrupt_step_dir(os.path.join(
                             self.directory, _layout.step_dirname(step))))
                break
            except (OSError, IOError) as e:
                if _metrics.ENABLED:
                    _metrics.CHECKPOINT_FAILURES.inc(
                        stage="save_attempt", reason=type(e).__name__)
                if attempt == attempts - 1:
                    raise CheckpointError(
                        f"checkpoint step {step} failed after "
                        f"{attempts} attempts: {e}") from e
                log.warning("checkpoint step %d attempt %d/%d failed "
                            "(%s); retrying in %.3fs", step, attempt + 1,
                            attempts, e, delay)
                time.sleep(delay)
                delay *= 2
        self._last_saved_step = step
        if _metrics.ENABLED:
            _metrics.CHECKPOINT_SAVE_SECONDS.observe(
                time.perf_counter() - t0)
            _metrics.CHECKPOINT_BYTES_WRITTEN.inc(written)
            _metrics.CHECKPOINT_LAST_STEP.set(step)
        if _journal.ENABLED:
            # durable: after a crash, the journal's last checkpoint_save
            # row IS the resume point an operator reaches for
            _journal.emit("checkpoint_save", step=step, durable=True,
                          bytes=written,
                          seconds=round(time.perf_counter() - t0, 6))
        try:
            self._gc()
        except Exception as e:  # noqa: BLE001 — GC must not fail a save
            log.warning("checkpoint GC failed: %s", e)
            if _metrics.ENABLED:
                _metrics.CHECKPOINT_FAILURES.inc(
                    stage="gc", reason=type(e).__name__)

    def _next_seq(self) -> int:
        # MUST stay lock-free: called with _write_lock held (see the
        # _seq comment in __init__ for the deadlock this prevents)
        return next(self._seq)

    # -- barrier -------------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> None:
        """Block until every queued save has committed; raise the first
        deferred write error if one occurred."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._pending > 0:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise CheckpointError(
                        f"wait() timed out with {self._pending} pending")
                self._lock.wait(remaining)
        self._raise_pending_error()

    def all_finished(self) -> bool:
        with self._lock:
            return self._pending == 0

    def _raise_pending_error(self) -> None:
        with self._lock:
            if self._errors:
                err = self._errors.pop(0)
                raise err

    def close(self) -> None:
        """Drain the queue and stop the writer thread."""
        try:
            self.wait()
        finally:
            with self._lock:
                self._closed = True
                self._lock.notify_all()
            if self._worker is not None:
                self._worker.join(timeout=5)

    # -- retention -----------------------------------------------------------
    def _pinned(self, step: int) -> bool:
        return self.keep_period is not None and step % self.keep_period == 0

    def _gc(self) -> None:
        # stale tmp dirs from crashed writers are always junk; only the
        # writer thread runs here, so no in-flight tmp can be caught
        for path in _layout.tmp_dirs(self.directory):
            shutil.rmtree(path, ignore_errors=True)
        if self.max_to_keep is None:
            return
        steps = _layout.all_steps(self.directory)
        disposable = [s for s in steps if not self._pinned(s)]
        for step in disposable[:max(0, len(disposable) - self.max_to_keep)]:
            shutil.rmtree(
                os.path.join(self.directory, _layout.step_dirname(step)),
                ignore_errors=True)

    # -- discovery -----------------------------------------------------------
    def all_steps(self) -> List[int]:
        return _layout.all_steps(self.directory)

    def latest_step(self) -> Optional[int]:
        return _layout.latest_step(self.directory)

    # -- restore -------------------------------------------------------------
    def restore(self, step: Optional[int] = None,
                with_manifest: bool = False):
        """Load the newest fully-valid checkpoint (or exactly ``step``).

        Auto mode (``step=None``) walks EVERY ``step_N`` dir
        newest-first: a torn or CRC-corrupt checkpoint increments the
        restore failure counter (stage="restore" — the page-the-oncall
        signal) and falls back to the previous valid step.  Explicit
        ``step`` raises ``CheckpointInvalidError`` loudly instead — the
        caller named a checkpoint, silently loading a different one
        would be a correctness bug.  Returns ``(step, state)`` (or
        ``(step, state, manifest)``) — ``None`` when the directory
        holds NO ``step_N`` candidates at all (the fresh-start case
        ``restore_or_initialize`` keys on).  When candidates exist but
        every one is invalid, raises ``CheckpointError`` listing each
        step scanned and why it was rejected (torn / crc / manifest /
        shard) — a directory FULL of damaged checkpoints is storage
        trouble the operator must see, not a silent fresh start that
        quietly discards the run."""
        self.wait()
        candidates = [int(step)] if step is not None \
            else sorted(_layout.raw_steps(self.directory), reverse=True)
        rejected: List[Tuple[int, str, str]] = []
        for cand in candidates:
            path = os.path.join(self.directory, _layout.step_dirname(cand))
            t0 = time.perf_counter()
            try:
                manifest, state = _layout.load_checkpoint_dir(path)
            except CheckpointInvalidError as e:
                if _metrics.ENABLED:
                    _metrics.CHECKPOINT_FAILURES.inc(
                        stage="restore", reason="invalid")
                if step is not None:
                    raise
                rejected.append((cand, getattr(e, "kind", "invalid"),
                                 str(e)))
                log.warning("skipping invalid checkpoint %s: %s", path, e)
                continue
            if _metrics.ENABLED:
                _metrics.CHECKPOINT_RESTORE_SECONDS.observe(
                    time.perf_counter() - t0)
            if _journal.ENABLED:
                _journal.emit("checkpoint_restore", step=cand,
                              durable=True,
                              seconds=round(time.perf_counter() - t0, 6))
            if with_manifest:
                return cand, state, manifest
            return cand, state
        if rejected:
            lines = "\n".join(
                f"  step {s}: [{kind}] {msg}" for s, kind, msg in rejected)
            raise CheckpointError(
                f"no valid checkpoint in {self.directory}: scanned "
                f"{len(rejected)} candidate(s) newest-first and rejected "
                f"every one —\n{lines}\n(torn = incomplete write, crc = "
                "bit-rot, manifest/shard = unreadable metadata or "
                "payload; see docs/checkpointing.md)")
        return None


# ---------------------------------------------------------------------------
# env-routed default manager (legacy callback path)
# ---------------------------------------------------------------------------
_ENV_MANAGERS: Dict[str, CheckpointManager] = {}
_ENV_LOCK = _san.make_lock("checkpoint.env_managers")


def _drain_env_managers() -> None:
    # the writer is a daemon thread: without this barrier the final
    # checkpoint of a legacy-callback run could still be in flight when
    # the interpreter exits — a durability regression vs the
    # synchronous legacy write the env routing replaces
    with _ENV_LOCK:
        managers = list(_ENV_MANAGERS.values())
    for mgr in managers:
        try:
            mgr.wait(timeout=300)
        except Exception as e:  # noqa: BLE001 — exiting; report, don't mask
            log.error("checkpoint flush at exit failed: %s", e)


atexit.register(_drain_env_managers)


def env_manager() -> Optional[CheckpointManager]:
    """The process-wide manager for ``MXNET_CHECKPOINT_DIR``, or None
    when the env is unset.  Read dynamically so tests (and long-lived
    jobs) may flip the env after import; one manager per directory."""
    root = os.environ.get("MXNET_CHECKPOINT_DIR")
    if not root:
        return None
    with _ENV_LOCK:
        mgr = _ENV_MANAGERS.get(root)
        if mgr is None:
            mgr = CheckpointManager(
                root, max_to_keep=int(getenv("MXNET_CHECKPOINT_KEEP", 5)))
            _ENV_MANAGERS[root] = mgr
        return mgr


# ---------------------------------------------------------------------------
# state packing conventions shared by the integrations
# ---------------------------------------------------------------------------
PARAM_PREFIX = "param:"
ARG_PREFIX = "arg:"
AUX_PREFIX = "aux:"
TRAINER_STATES_KEY = "trainer:states"
OPTIMIZER_STATES_KEY = "optimizer:states"
SYMBOL_KEY = "symbol:json"


def pack_module_state(symbol, arg_params: Dict, aux_params: Dict,
                      optimizer_states: Optional[bytes] = None) -> Dict:
    state: Dict = {f"{ARG_PREFIX}{k}": v for k, v in arg_params.items()}
    state.update({f"{AUX_PREFIX}{k}": v for k, v in aux_params.items()})
    if symbol is not None:
        state[SYMBOL_KEY] = symbol.tojson().encode("utf-8")
    if optimizer_states is not None:
        state[OPTIMIZER_STATES_KEY] = optimizer_states
    return state


def unpack_module_state(state: Dict):
    """→ (arg_params, aux_params, optimizer_states_bytes_or_None,
    symbol_json_or_None) with arrays left as numpy."""
    arg_p = {k[len(ARG_PREFIX):]: v for k, v in state.items()
             if k.startswith(ARG_PREFIX)}
    aux_p = {k[len(AUX_PREFIX):]: v for k, v in state.items()
             if k.startswith(AUX_PREFIX)}
    opt = state.get(OPTIMIZER_STATES_KEY)
    sym_json = state.get(SYMBOL_KEY)
    if isinstance(sym_json, bytes):
        sym_json = sym_json.decode("utf-8")
    return arg_p, aux_p, opt, sym_json


def _as_param_dict(params):
    """Accept a gluon Block, ParameterDict, or {name: Parameter}.
    Returns ``{stripped_name: Parameter}`` — names are stored WITHOUT
    the instance name-scope prefix (the ``save_params`` /
    ``strip_prefix`` idiom), so a checkpoint written by
    ``hybridsequential0_`` restores into a fresh ``hybridsequential1_``
    net."""
    from ..gluon.parameter import ParameterDict
    prefix = ""
    if hasattr(params, "collect_params"):
        prefix = getattr(params, "prefix", "")
        params = params.collect_params()
    if isinstance(params, ParameterDict):
        prefix = prefix or params.prefix
        out = {}
        for name in params.keys():
            if not name.startswith(prefix):
                prefix = ""  # mixed scopes: fall back to full names
                break
        for name in params.keys():
            out[name[len(prefix):]] = params[name]
        return out
    if isinstance(params, dict):
        return params
    raise MXNetError("expected a gluon Block, ParameterDict, or dict of "
                     f"Parameters, got {type(params)}")


def save_trainer(manager: CheckpointManager, step: int, params,
                 trainer=None, extra_state: Optional[Dict] = None,
                 block: bool = False) -> None:
    """Checkpoint a gluon training job: parameters (+ aux via the
    ParameterDict) and — when ``trainer`` is given — the full optimizer
    state INCLUDING 2-bit compression residuals (the
    ``Trainer.get_states_bytes`` sentinel-wrapped payload), so a
    resumed run continues the same quantization trajectory."""
    pd = _as_param_dict(params)
    state: Dict = {f"{PARAM_PREFIX}{name}": p.data()
                   for name, p in pd.items()}
    signatures = {}
    if trainer is not None:
        state[TRAINER_STATES_KEY] = trainer.get_states_bytes()
        if trainer._bucket_sig is not None:
            signatures["trainer_bucket_sig"] = repr(trainer._bucket_sig)
        # read the policy the training ACTUALLY ran under (the updater's
        # dtype_policy follows the last executed step; the whole-step
        # fallback resets it to f32) — the MXNET_AMP env var would lie
        # when whole-step fell back and AMP was inert
        upds = getattr(trainer, "_updaters", None) or []
        policy = getattr(upds[0], "dtype_policy", "f32") if upds else "f32"
        # stamp the EFFECTIVE policy unconditionally — "f32" included —
        # so a resume under a different MXNET_AMP is loud in BOTH
        # directions (f32 checkpoint resumed bf16 is just as much a
        # trajectory change as the reverse; restore_trainer checks)
        signatures["amp_policy"] = policy
    # stamp the GSPMD mesh SHAPE unconditionally ("replicated" when no
    # mesh is ambient) — same both-directions discipline as amp_policy,
    # but restore RAISES on mismatch: params sliced for one topology
    # loaded onto another is silent corruption, not a trajectory change
    from ..parallel.mesh import current_mesh, mesh_signature
    signatures["mesh_signature"] = mesh_signature(current_mesh())
    if extra_state:
        overlap = set(extra_state) & set(state)
        if overlap:
            raise MXNetError(f"extra_state collides with packed keys: "
                             f"{sorted(overlap)}")
        state.update(extra_state)
    manager.save(step, state, signatures=signatures, block=block)


def restore_trainer(manager: CheckpointManager, params, trainer=None,
                    step: Optional[int] = None,
                    ctx=None) -> Optional[int]:
    """Load the newest valid checkpoint into ``params`` (and
    ``trainer``).  Returns the restored step, or None when the
    directory holds no valid checkpoint.  Missing parameters raise —
    a half-restored model must never train silently."""
    res = manager.restore(step, with_manifest=True)
    if res is None:
        return None
    got_step, state, manifest = res
    saved_amp = (manifest.get("signatures") or {}).get("amp_policy")
    if saved_amp is not None:
        try:
            # the saved stamp records the EFFECTIVE policy (what the
            # training actually ran), so compare against what this
            # process can effectively run: MXNET_AMP only applies
            # inside the whole-step program — with whole-step off the
            # resume is f32 no matter what MXNET_AMP says
            from ..base import getenv
            from ..gluon.wholestep import amp_policy
            cur = amp_policy() if getenv("MXNET_WHOLE_STEP", False) \
                else "f32"
        except Exception:  # noqa: BLE001
            cur = "f32"
        if cur != saved_amp:
            # a resume under a different precision policy is VALID but
            # sits on a different numeric trajectory — say so loudly.
            # `cur` is the CONFIGURED policy; if whole-step falls back
            # at runtime the effective precision is f32 regardless, a
            # case only the compiler's own fallback warning can catch
            log.warning(
                "checkpoint step %s was written under effective "
                "MXNET_AMP=%s but this process is configured for %s — "
                "resuming changes the numeric trajectory (loss-scaler "
                "state restores regardless; if whole-step falls back, "
                "the run is f32 whatever MXNET_AMP says)",
                got_step, saved_amp, cur)
    saved_mesh = (manifest.get("signatures") or {}).get("mesh_signature")
    if saved_mesh is not None:
        from ..parallel.mesh import current_mesh, mesh_signature
        cur_mesh = mesh_signature(current_mesh())
        if cur_mesh != saved_mesh:
            # LOUD, unlike the amp warning: optimizer state, bucket
            # residuals, and the params' committed placements were all
            # written for the saved topology — loading them onto a
            # different mesh shape silently mis-shards the run.
            # (Elastic reshard-on-restore is the ROADMAP follow-up;
            # until it lands, mismatches must stop the resume.)
            raise CheckpointError(
                f"checkpoint step {got_step} was written on mesh "
                f"[{saved_mesh}] but this process runs mesh "
                f"[{cur_mesh}] — set MXNET_MESH_BATCH/MXNET_MESH_MODEL "
                f"(or set_current_mesh) to the saved shape, or start a "
                f"fresh run directory")
    pd = _as_param_dict(params)
    missing = [name for name in pd
               if f"{PARAM_PREFIX}{name}" not in state]
    if missing:
        raise CheckpointError(
            f"checkpoint step {got_step} lacks parameters {missing[:5]}"
            f"{'...' if len(missing) > 5 else ''}")
    for name, p in pd.items():
        arr = state[f"{PARAM_PREFIX}{name}"]
        try:
            pctx = p.list_ctx()
        except Exception:  # noqa: BLE001 — uninitialized, no deferred ctx
            from ..context import cpu
            pctx = [ctx] if ctx is not None else [cpu()]
        p._load_init(arr, pctx)
    if trainer is not None and TRAINER_STATES_KEY in state:
        trainer.set_states_bytes(state[TRAINER_STATES_KEY])
    if _journal.ENABLED:
        # the durable stitch between incarnations: the restarted
        # process resumes the SAME run id (journal.py continuity), and
        # this row marks where training re-entered the step sequence
        _journal.resume_marker(got_step, source="restore_trainer")
    return got_step


def restore_or_initialize(manager: CheckpointManager, params, trainer=None,
                          initializer=None, ctx=None,
                          step: Optional[int] = None) -> Optional[int]:
    """Auto-resume convenience: restore the latest valid checkpoint,
    or — when none exists — initialize the parameters fresh.  Returns
    the restored step (None = initialized from scratch)::

        mgr = mx.checkpoint.CheckpointManager(dir, max_to_keep=5)
        start = mx.checkpoint.restore_or_initialize(
            mgr, net, trainer, initializer=mx.init.Xavier()) or 0
        for step in range(start, total_steps):
            ... train ...
            if step % 100 == 0:
                mx.checkpoint.save_trainer(mgr, step, net, trainer)
    """
    got = restore_trainer(manager, params, trainer=trainer, step=step,
                          ctx=ctx)
    if got is not None:
        return got
    pd = _as_param_dict(params)
    from ..gluon.parameter import ParameterDict
    holder = params.collect_params() if hasattr(params, "collect_params") \
        else params
    if isinstance(holder, ParameterDict):
        holder.initialize(init=initializer, ctx=ctx)
    else:
        for p in pd.values():
            p.initialize(init=initializer, ctx=ctx)
    if _journal.ENABLED:
        _journal.emit("run_initialized", durable=True,
                      directory=manager.directory)
    return None
