"""On-disk checkpoint layout: atomic step directories with a validated
manifest.

One checkpoint = one directory::

    <root>/
      step_200/
        manifest.json          # commit record: entries, shapes, CRCs
        shard_0.npz            # array payload, size-capped shards
        shard_1.npz
      step_400/ ...
      .tmp-step_600-1234-7/    # in-flight write; ignored by discovery

The write protocol makes a torn write IMPOSSIBLE to load:

  1. everything is written into a ``.tmp-*`` sibling directory;
  2. the manifest (which carries per-entry CRC32s and per-shard sizes)
     is written last, via its own temp-file + ``os.replace``;
  3. the directory is fsynced and renamed (``os.replace``) to
     ``step_N`` — the rename is the commit point, atomic on POSIX.

A crash at any earlier point leaves only a ``.tmp-*`` directory, which
discovery skips and the manager's GC removes.  A checkpoint that lost a
shard, had its manifest truncated, or whose array bytes rot on disk
fails validation (existence + size at scan time, CRC32 at load time)
and is treated as absent — never loaded.

State model: a flat ``{name: value}`` dict where each value is an
array (NDArray / numpy / jax), ``bytes`` (opaque blobs: optimizer-state
pickles, symbol JSON), or a JSON-able python value.  Arrays and bytes
land in npz shards under generated keys; JSON values inline into the
manifest.
"""
from __future__ import annotations

import json
import os
import re
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError, getenv

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_PREFIX = ".tmp-"


class CheckpointInvalidError(MXNetError):
    """A checkpoint directory failed validation (torn write, missing
    shard, CRC mismatch, unreadable manifest).  ``kind`` names the
    rejection class — ``manifest`` (unreadable/unsupported/drifted
    manifest), ``torn`` (missing shard / size mismatch / missing
    entry: an incomplete write), ``crc`` (bit-rot: stored CRC32
    disagrees), ``shard`` (shard file unreadable) — so
    ``CheckpointManager.restore``'s exhaustion diagnostics can say WHY
    each candidate was rejected."""

    def __init__(self, msg: str, kind: str = "invalid"):
        super().__init__(msg)
        self.kind = kind


def step_dirname(step: int) -> str:
    return f"step_{int(step)}"


def parse_step(name: str) -> Optional[int]:
    m = _STEP_RE.match(name)
    return int(m.group(1)) if m else None


def is_tmp_dirname(name: str) -> bool:
    return name.startswith(_TMP_PREFIX)


# ---------------------------------------------------------------------------
# state snapshot (device -> host, eager)
# ---------------------------------------------------------------------------
def snapshot_state(state: Dict) -> Dict[str, tuple]:
    """Copy every entry off the device / out of caller-mutable memory
    NOW, so training may donate or overwrite its buffers the moment
    ``save()`` returns.  Returns ``{name: (kind, payload)}`` with kind
    in {'array', 'bytes', 'json'}."""
    if not isinstance(state, dict):
        raise MXNetError("checkpoint state must be a {name: value} dict, "
                         f"got {type(state)}")
    out: Dict[str, tuple] = {}
    for name, value in state.items():
        if not isinstance(name, str) or not name:
            raise MXNetError(f"state keys must be non-empty str, got {name!r}")
        if isinstance(value, (bytes, bytearray, memoryview)):
            out[name] = ("bytes", bytes(value))
        elif hasattr(value, "asnumpy"):  # NDArray
            # asnumpy() already hands back an OWNED writable host copy
            # (NDArray contract) — no second copy needed
            out[name] = ("array", value.asnumpy())
        elif isinstance(value, _np.ndarray) or hasattr(value, "__array__"):
            # numpy / jax array — force a real host copy: a jax buffer
            # about to be DONATED by the next step must not back this
            out[name] = ("array", _np.array(value, copy=True))
        else:
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                raise MXNetError(
                    f"state['{name}'] ({type(value).__name__}) is not an "
                    "array, bytes, or JSON-able value") from None
            out[name] = ("json", value)
    return out


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------
def _fsync_on() -> bool:
    # MXNET_CHECKPOINT_FSYNC=0 trades durability-past-OS-crash for
    # speed (atomicity vs PROCESS crash still holds — that comes from
    # the rename, not the fsyncs).  Read per-write so tests can flip it.
    return bool(getenv("MXNET_CHECKPOINT_FSYNC", True))


def _fsync_file(path: str) -> None:
    if not _fsync_on():
        return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    if not _fsync_on():
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without O_RDONLY dir opens: best-effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _entry_bytes(kind: str, payload) -> _np.ndarray:
    if kind == "bytes":
        return _np.frombuffer(payload, dtype=_np.uint8)
    return payload


def write_checkpoint_dir(root: str, step: int, snap: Dict[str, tuple],
                         tmp_token: str, meta: Optional[dict] = None,
                         signatures: Optional[dict] = None,
                         shard_cap_bytes: Optional[int] = None) -> int:
    """Write one checkpoint under ``root`` using the tmp+rename
    protocol.  ``snap`` is ``snapshot_state`` output.  Returns payload
    bytes written.  Raises OSError (and friends) on IO failure — the
    manager retries around this."""
    import time as _time
    if shard_cap_bytes is None:
        shard_cap_bytes = int(float(getenv("MXNET_CHECKPOINT_SHARD_MB",
                                           256.0)) * (1 << 20))
    final = os.path.join(root, step_dirname(step))
    tmp = os.path.join(root, f"{_TMP_PREFIX}{step_dirname(step)}-{tmp_token}")
    os.makedirs(root, exist_ok=True)
    if os.path.exists(tmp):
        import shutil
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    # size-capped shard packing, insertion order (stable across runs)
    entries: Dict[str, dict] = {}
    shards: List[Dict[str, _np.ndarray]] = []
    shard_fill = 0
    for name, (kind, payload) in snap.items():
        if kind == "json":
            entries[name] = {"kind": "json", "value": payload}
            continue
        arr = _entry_bytes(kind, payload)
        nbytes = int(arr.nbytes)
        if not shards or (shard_fill and shard_fill + nbytes > shard_cap_bytes):
            shards.append({})
            shard_fill = 0
        sid = len(shards) - 1
        key = f"e_{len(shards[sid])}"
        shards[sid][key] = arr
        shard_fill += nbytes
        entry = {"kind": kind, "shard": f"shard_{sid}.npz", "key": key,
                 "crc32": zlib.crc32(_np.ascontiguousarray(arr).tobytes())}
        if kind == "array":
            entry["shape"] = list(arr.shape)
            entry["dtype"] = str(arr.dtype)
        entries[name] = entry

    written = 0
    shard_meta = {}
    for sid, shard in enumerate(shards):
        fname = f"shard_{sid}.npz"
        path = os.path.join(tmp, fname)
        with open(path, "wb") as f:
            _np.savez(f, **shard)
        _fsync_file(path)
        shard_meta[fname] = {"bytes": os.path.getsize(path)}
        written += shard_meta[fname]["bytes"]

    from .. import __version__
    manifest = {
        "format_version": FORMAT_VERSION,
        "step": int(step),
        "time": _time.time(),
        "library_version": __version__,
        "entries": entries,
        "shards": shard_meta,
        "signatures": signatures or {},
        "meta": meta or {},
    }
    mpath = os.path.join(tmp, MANIFEST)
    mtmp = mpath + ".tmp"
    with open(mtmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(mtmp, mpath)
    _fsync_dir(tmp)

    if os.path.exists(final):
        # re-save of an existing step: replace it (rare — a resumed run
        # re-reaching the same step).  The window between rmtree and
        # rename only ever risks THIS step; older steps stay intact.
        import shutil
        shutil.rmtree(final)
    os.replace(tmp, final)
    _fsync_dir(root)
    return written


# ---------------------------------------------------------------------------
# validate / read
# ---------------------------------------------------------------------------
def read_manifest(step_dir: str) -> dict:
    mpath = os.path.join(step_dir, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointInvalidError(
            f"{step_dir}: unreadable manifest ({e})",
            kind="manifest") from None
    if manifest.get("format_version") != FORMAT_VERSION:
        raise CheckpointInvalidError(
            f"{step_dir}: unsupported format_version "
            f"{manifest.get('format_version')!r}", kind="manifest")
    return manifest


def quick_validate(step_dir: str) -> dict:
    """Cheap scan-time validation: manifest parses, every shard exists
    with the recorded size.  Returns the manifest."""
    manifest = read_manifest(step_dir)
    for fname, info in manifest.get("shards", {}).items():
        path = os.path.join(step_dir, fname)
        try:
            size = os.path.getsize(path)
        except OSError:
            raise CheckpointInvalidError(
                f"{step_dir}: missing shard {fname}",
                kind="torn") from None
        if size != info.get("bytes"):
            raise CheckpointInvalidError(
                f"{step_dir}: shard {fname} is {size} bytes, manifest "
                f"says {info.get('bytes')}", kind="torn")
    return manifest


def load_checkpoint_dir(step_dir: str) -> Tuple[dict, Dict]:
    """Full validation + load: every entry's CRC32 must match the
    manifest.  Returns ``(manifest, state)`` with arrays as numpy,
    bytes entries as bytes, json entries verbatim."""
    manifest = quick_validate(step_dir)
    loaded_shards: Dict[str, dict] = {}
    for fname in manifest.get("shards", {}):
        path = os.path.join(step_dir, fname)
        try:
            with _np.load(path, allow_pickle=False) as z:
                loaded_shards[fname] = {k: z[k] for k in z.keys()}
        except Exception as e:  # noqa: BLE001 — any zip/npy damage
            raise CheckpointInvalidError(
                f"{step_dir}: shard {fname} unreadable ({e})",
                kind="shard") from None
    state: Dict = {}
    for name, entry in manifest["entries"].items():
        kind = entry["kind"]
        if kind == "json":
            state[name] = entry["value"]
            continue
        shard = loaded_shards.get(entry["shard"], {})
        if entry["key"] not in shard:
            raise CheckpointInvalidError(
                f"{step_dir}: entry '{name}' missing from {entry['shard']}",
                kind="torn")
        arr = shard[entry["key"]]
        crc = zlib.crc32(_np.ascontiguousarray(arr).tobytes())
        if crc != entry["crc32"]:
            raise CheckpointInvalidError(
                f"{step_dir}: CRC mismatch on '{name}' "
                f"(stored {entry['crc32']}, computed {crc})", kind="crc")
        if kind == "bytes":
            state[name] = arr.tobytes()
        else:
            if list(arr.shape) != entry.get("shape") or \
                    str(arr.dtype) != entry.get("dtype"):
                raise CheckpointInvalidError(
                    f"{step_dir}: entry '{name}' shape/dtype drifted from "
                    "manifest", kind="manifest")
            state[name] = arr
    return manifest, state


# ---------------------------------------------------------------------------
# discovery
# ---------------------------------------------------------------------------
def raw_steps(root: str) -> List[int]:
    """Every ``step_N`` directory, valid or not (restore walks this so
    invalid checkpoints are COUNTED as they are skipped)."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return sorted(s for s in (parse_step(n) for n in names)
                  if s is not None)


def all_steps(root: str) -> List[int]:
    """Sorted steps whose directories pass quick validation.  Junk
    files, in-flight ``.tmp-*`` dirs, and torn checkpoints are
    silently skipped — discovery never raises on bad entries."""
    steps = []
    for step in raw_steps(root):
        try:
            quick_validate(os.path.join(root, step_dirname(step)))
        except CheckpointInvalidError:
            continue
        steps.append(step)
    return sorted(steps)


def latest_step(root: str) -> Optional[int]:
    steps = all_steps(root)
    return steps[-1] if steps else None


def tmp_dirs(root: str) -> List[str]:
    try:
        names = os.listdir(root)
    except OSError:
        return []
    return [os.path.join(root, n) for n in names if is_tmp_dirname(n)]
