"""Emergency-save hooks: flush a checkpoint on SIGTERM / interpreter
exit, so a preempted worker (spot VM reclaim, k8s pod eviction — the
cloud sends SIGTERM and gives you seconds) resumes from its last step
instead of its last periodic checkpoint.
"""
from __future__ import annotations

import atexit
import logging
import signal
import sys
import threading
from typing import Callable, Optional, Tuple

from .manager import CheckpointManager

log = logging.getLogger(__name__)


class _PreemptionHook:
    def __init__(self, manager: CheckpointManager,
                 state_fn: Callable[[], Tuple[int, dict]],
                 signals, exit_on_signal: bool):
        self.manager = manager
        self.state_fn = state_fn
        self.exit_on_signal = exit_on_signal
        self._fired = False
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("checkpoint.hooks.fired")
        self._prev = {}
        self._signals = tuple(signals)
        self._atexit_registered = False

    def _save_once(self, why: str) -> None:
        with self._lock:
            if self._fired:
                return
            self._fired = True
        step = None
        try:
            step, state = self.state_fn()
            if self.manager._last_saved_step == int(step) and \
                    self.manager.all_finished():
                log.info("preemption hook (%s): step %d already saved",
                         why, step)
                return
            log.warning("preemption hook (%s): saving checkpoint step %d "
                        "to %s", why, step, self.manager.directory)
            # synchronous: the process is about to die, there is no
            # background left to rely on
            self.manager.save(int(step), state, block=True,
                              meta={"emergency": why})
            # and drain anything training had queued before the signal —
            # the daemon writer thread dies with the process
            self.manager.wait(timeout=300)
        except Exception as e:  # noqa: BLE001 — dying anyway; log, don't mask
            log.error("preemption-hook save failed: %s", e)
        finally:
            # a SIGTERM'd run leaves a TIMELINE, not just weights: the
            # flight ring holds the last ~MXNET_FLIGHT_RING phases per
            # thread — exactly the "what was it doing when the cloud
            # reclaimed it" evidence.  AFTER the save (its own
            # checkpoint_block/_write spans belong in the dump), inline
            # (this process is exiting; no background thread survives),
            # and never allowed to mask a save failure.
            self._dump_flight()
            # the run journal's TERMINAL entry: fsync'd before the
            # process exits, so the restarted incarnation (same run id)
            # and the offline reporter both see why this one ended
            try:
                from ..observability import journal as _journal
                if _journal.ENABLED:
                    _journal.emit("preempted", step=step, durable=True,
                                  why=why)
            except Exception as e:  # noqa: BLE001 — dying anyway
                log.error("preemption-hook journal entry failed: %s", e)

    @staticmethod
    def _dump_flight() -> None:
        try:
            from ..observability import flight as _flight
            if _flight.ENABLED:
                path = _flight.dump(reason="preempt")
                log.warning("preemption hook: flight timeline dumped to %s",
                            path)
        except Exception as e:  # noqa: BLE001
            log.error("preemption-hook flight dump failed: %s", e)

    def _on_signal(self, signum, frame):
        self._save_once(f"signal {signum}")
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif self.exit_on_signal:
            # 128+signum: the exit status a signal-terminated process
            # reports, so supervisors still see "killed by SIGTERM"
            sys.exit(128 + signum)

    def _on_atexit(self):
        self._save_once("atexit")

    def install(self, use_atexit: bool) -> None:
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._on_signal)
        if use_atexit:
            atexit.register(self._on_atexit)
            self._atexit_registered = True

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError):  # non-main thread / exotic sig
                pass
        self._prev.clear()
        if self._atexit_registered:
            try:
                atexit.unregister(self._on_atexit)
            except Exception:  # noqa: BLE001
                pass
            self._atexit_registered = False


def install_preemption_hook(
        manager: CheckpointManager,
        state_fn: Callable[[], Tuple[int, dict]],
        signals=(signal.SIGTERM,),
        use_atexit: bool = True,
        exit_on_signal: bool = True) -> Callable[[], None]:
    """Arrange an emergency synchronous checkpoint on SIGTERM (and,
    optionally, normal interpreter exit).

    ``state_fn() -> (step, state)`` is called AT SAVE TIME from the
    main thread, so it should read live training state (e.g. close
    over the trainer and a step counter).  The save runs at most once
    per install, is skipped when ``step`` is already on disk, and uses
    the manager's full retry + atomic-commit path.  Returns an
    ``uninstall()`` callable that restores the previous handlers.

    Must be called from the main thread (CPython restricts
    ``signal.signal`` to it).
    """
    hook = _PreemptionHook(manager, state_fn, signals, exit_on_signal)
    hook.install(use_atexit)
    return hook.uninstall
