"""Fault-tolerant checkpointing & auto-resume (ISSUE 5).

The durable-state subsystem: async atomic snapshots, CRC-validated
restore with fallback, retention GC, auto-resume wiring for
``gluon.Trainer`` / ``Module.fit(checkpoint_dir=...)`` / the serving
``BucketedPredictor`` hot reload, and a SIGTERM/atexit emergency-save
hook.  See ``docs/checkpointing.md``.

Quick start::

    import mxnet_tpu as mx
    mgr = mx.checkpoint.CheckpointManager("ckpts", max_to_keep=5,
                                          keep_period=1000)
    start = mx.checkpoint.restore_or_initialize(
        mgr, net, trainer, initializer=mx.init.Xavier()) or 0
    stop = mx.checkpoint.install_preemption_hook(
        mgr, lambda: (step, {"param:" + k: p.data()
                             for k, p in net.collect_params().items()}))
    for step in range(start, total):
        ...
        if step % 200 == 0:
            mx.checkpoint.save_trainer(mgr, step, net, trainer)
    mgr.wait()
"""
from .layout import (CheckpointInvalidError, all_steps, latest_step,
                     load_checkpoint_dir, quick_validate, read_manifest,
                     step_dirname)
from .manager import (ARG_PREFIX, AUX_PREFIX, OPTIMIZER_STATES_KEY,
                      PARAM_PREFIX, SYMBOL_KEY, TRAINER_STATES_KEY,
                      CheckpointError, CheckpointManager, env_manager,
                      pack_module_state, restore_or_initialize,
                      restore_trainer, save_trainer, unpack_module_state)
from .hooks import install_preemption_hook

__all__ = [
    "CheckpointManager", "CheckpointError", "CheckpointInvalidError",
    "all_steps", "latest_step", "step_dirname", "read_manifest",
    "quick_validate", "load_checkpoint_dir", "env_manager",
    "save_trainer", "restore_trainer", "restore_or_initialize",
    "pack_module_state", "unpack_module_state",
    "install_preemption_hook",
    "PARAM_PREFIX", "ARG_PREFIX", "AUX_PREFIX", "TRAINER_STATES_KEY",
    "OPTIMIZER_STATES_KEY", "SYMBOL_KEY",
]
