"""Executor: a bound, compiled symbolic graph.

Reference parity: `include/mxnet/executor.h:53` + `src/executor/
graph_executor.cc` (GraphExecutor::Init/Forward/Backward, memory planning,
op bulking) + `python/mxnet/executor.py`.  TPU-native realization:
  - bind-time nnvm passes → one `jax.jit` of the whole-graph interpreter
    (forward) and one of forward+vjp (fused forward-backward).  XLA does
    shape specialization, memory planning, fusion, and scheduling — the
    reference's PlanMemory/AttachOpExecs/segment-bulking machinery
    (graph_executor.cc:908,913,1350) has no hand-written analog.
  - gradient graph (nnvm Gradient pass) → `jax.vjp` over the interpreter.
  - `MXNET_BACKWARD_DO_MIRROR` recompute → `jax.checkpoint` (remat) when
    env MXNET_BACKWARD_DO_MIRROR=1 (parity: graph_executor.cc:282-305).
  - `forward_backward()` runs outputs+grads+aux in ONE compiled call — the
    path Module.fit uses, giving a single XLA executable per training step.
  - separate forward()/backward() keep exact reference semantics (same
    dropout mask, aux updated once) by snapshotting forward's inputs/key.
  - group2ctx model parallelism: per-group `jax.device_put` in an eager
    per-node mode (PlaceDevice-pass analog, graph_executor.cc:411).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .base import MXNetError, getenv, maybe_enable_compile_cache
from .context import Context
from .faultinject import fire as _fi_fire
from .ndarray import NDArray
from .observability import introspect as _introspect
from .observability import memory as _memory
from .observability import metrics as _metrics
from .observability.tracing import trace_span
from .symbol.graph import GraphPlan
from . import random as _random


def _device_of(a):
    """Single device an array lives on, or None if sharded/unknown."""
    try:
        devs = a.devices()
        return next(iter(devs)) if len(devs) == 1 else None
    except Exception:
        return None


class Executor:
    def __init__(self, symbol, ctx, args: Dict[str, NDArray],
                 args_grad: Dict[str, NDArray], grad_req: Dict[str, str],
                 aux_states: Dict[str, NDArray], group2ctx=None,
                 shared_exec: Optional["Executor"] = None,
                 mesh=None, data_shard_args=()):
        # persistent XLA compile cache (MXNET_COMPILE_CACHE_DIR): wired
        # at bind time so training executors share the on-disk cache the
        # serving path uses — a restart skips recompiles in both worlds
        maybe_enable_compile_cache()
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self.arg_dict = dict(args)
        self.grad_dict = dict(args_grad or {})
        self.grad_req = dict(grad_req)
        self.aux_dict = dict(aux_states or {})
        self.group2ctx = group2ctx
        self._plan = GraphPlan(symbol)
        self._plan.specialize_init_shapes(
            {n: a.shape for n, a in self.arg_dict.items() if a is not None})
        # bucketing / reshape: share the compiled-function cache so XLA
        # executables are reused across executors of the same symbol family
        self._jit_cache = shared_exec._jit_cache if shared_exec is not None else {}
        self._grad_names = [n for n in self._plan.arg_names
                            if self.grad_req.get(n, "null") != "null"]
        self._monitor = None
        self._monitor_all = False
        self._outputs_cache: Optional[List[NDArray]] = None
        self._snapshot = None  # (arg_vals, aux_vals, key) of last forward
        self._pending_grads = None  # grads held by a train-mode forward()
        # lazy train-mode forward (VERDICT r3 #6): until this executor's
        # backward() is seen once, forward(is_train=True) runs ONLY the
        # forward program — Monitor taps / MC eval never pay the vjp.
        # After the first backward() the fused fwd+vjp runs eagerly again
        # so the forward(); backward() training pattern stays one
        # compiled step.
        self._seen_backward = False
        self._remat = bool(getenv("MXNET_BACKWARD_DO_MIRROR", 0))
        # sqrt(N) contiguous jax.checkpoint segments (graph.py
        # _run_segmented) — a WHOLE-graph checkpoint saves nothing;
        # MXNET_MIRROR_SEGMENTS overrides the sqrt default
        nsteg = int(getenv("MXNET_MIRROR_SEGMENTS", 0) or 0)
        self._mirror_segments = nsteg or max(
            2, int(len(self._plan.steps) ** 0.5))
        # rows-only embedding grads (VERDICT r3 #8): args eligible for
        # the in-graph rsp rewrite — weight of Embedding(sparse_grad)
        # steps, grad_req 'write', no remat/group2ctx interplay.  The
        # fused program differentiates an injected zero 'dummy' of the
        # lookup's OUTPUT shape instead of the O(vocab) weight, so the
        # dense V×D gradient buffer never exists on device.
        self._rsp_grad_args = {}
        if not self._remat and not group2ctx:
            for n, lst in self._plan.sparse_grad_args().items():
                if self.grad_req.get(n) == "write":
                    self._rsp_grad_args[n] = tuple(lst)
        # SPMD data parallelism: batch args sharded on 'dp' over the mesh,
        # params replicated; XLA all-reduces gradients over ICI.  This is the
        # TPU redesign of DataParallelExecutorGroup (SURVEY.md §2.3).
        self._mesh = mesh
        self._data_shard_args = set(data_shard_args)
        # introspection captures done, keyed like the _jit_cache entries
        # so a plan-key change (re-specialized shapes) re-notes the new
        # program instead of keeping the first one's flops forever
        self._noted = set()

    @property
    def _plan_key(self):
        """Cache key for shared _jit_cache entries: same symbol + same
        init-shape specialization → same executable family (reshape of the
        same symbol reuses jax's per-shape cache; distinct bucket symbols
        or begin-state specializations get their own closures)."""
        ov = getattr(self._plan, "init_overrides", {})
        # the symbol object itself (identity hash) — kept alive by the
        # cache entry, so ids can't be recycled across dead symbols
        return (self._symbol,
                tuple(sorted((si, tuple(p.get("shape", ())))
                             for si, p in ov.items())))

    # -- compiled entry points ---------------------------------------------
    @property
    def _fwd(self):
        key = ("fwd", self._plan_key)
        if key not in self._jit_cache:
            if _metrics.ENABLED:
                _metrics.JIT_CACHE_MISSES.inc()
            plan = self._plan
            self._jit_cache[key] = jax.jit(
                lambda a, x, k, t: plan.run(a, x, k, t), static_argnums=(3,))
        elif _metrics.ENABLED:
            _metrics.JIT_CACHE_HITS.inc()
        return self._jit_cache[key]

    @property
    def _fwd_bwd(self):
        key = ("fwd_bwd", self._plan_key, tuple(self._grad_names),
               tuple(sorted(self._rsp_grad_args)))
        if key not in self._jit_cache:
            if _metrics.ENABLED:
                _metrics.JIT_CACHE_MISSES.inc()
            plan = self._plan
            rsp_map = dict(self._rsp_grad_args)
            grad_names = [n for n in self._grad_names if n not in rsp_map]
            remat = self._remat
            segN = self._mirror_segments

            def fb(arg_vals, aux_vals, key_, ograds):
                others = {k: v for k, v in arg_vals.items() if k not in grad_names}
                # one zero dummy per sparse-embedding step, shaped like
                # the lookup OUTPUT (tokens × dim, not vocab × dim)
                dummies = {}
                for n, lst in sorted(rsp_map.items()):
                    w = arg_vals[n]
                    for si, dvar in lst:
                        dummies[si] = jnp.zeros(
                            tuple(arg_vals[dvar].shape) + tuple(w.shape[1:]),
                            w.dtype)

                def fwd(gvals, dums):
                    merged = dict(others)
                    merged.update(gvals)
                    overrides, ids_out = {}, {}

                    def make_ov(si):
                        def ov(p, ins):
                            # clip BEFORE recording: the recorded ids are
                            # the rsp row indices, and an unclipped OOB id
                            # would drop/misroute its gradient where the
                            # dense vjp of take(mode='clip') scatters it
                            # into the clipped row
                            ids = jnp.clip(ins[0].astype(jnp.int32), 0,
                                           ins[1].shape[0] - 1)
                            ids_out[si] = ids
                            return (jnp.take(
                                jax.lax.stop_gradient(ins[1]), ids,
                                axis=0) + dums[si],)
                        return ov

                    for n, lst in rsp_map.items():
                        for si, _ in lst:
                            overrides[si] = make_ov(si)
                    res = plan.run(merged, aux_vals, key_, True,
                                   step_overrides=overrides or None,
                                   segments=segN if remat else 1)
                    return res, ids_out

                (outs, new_aux), vjp_fn, ids_out = jax.vjp(
                    fwd, {n: arg_vals[n] for n in grad_names}, dummies,
                    has_aux=True)
                cots = [og if og is not None else jnp.ones(o.shape, o.dtype)
                        for og, o in zip(ograds, outs)]
                zero_aux = jax.tree_util.tree_map(jnp.zeros_like, new_aux)
                grads, gdum = vjp_fn((cots, zero_aux))
                rsp_grads = {}
                for n, lst in sorted(rsp_map.items()):
                    rowdim = tuple(arg_vals[n].shape[1:])
                    ids = jnp.concatenate(
                        [ids_out[si].reshape(-1) for si, _ in lst])
                    vals = jnp.concatenate(
                        [gdum[si].reshape((-1,) + rowdim) for si, _ in lst])
                    rsp_grads[n] = (ids, vals)
                return outs, new_aux, grads, rsp_grads

            self._jit_cache[key] = jax.jit(fb)
        elif _metrics.ENABLED:
            _metrics.JIT_CACHE_HITS.inc()
        return self._jit_cache[key]

    # -- public API ---------------------------------------------------------
    def _gather(self, kwargs):
        dev = None if self._mesh is not None else self._ctx.jax_device()
        for k, v in kwargs.items():
            if k in self.arg_dict:
                val = (v._data if isinstance(v, NDArray)
                       else jnp.asarray(v)).astype(self.arg_dict[k].dtype)
                # batch data may arrive on another device (e.g. a CPU-side
                # iterator feeding a TPU-bound executor) — move it to the
                # executor's context, like the reference's load_data copyto
                # (src/executor exec_group _load_general)
                if dev is not None and _device_of(val) != dev:
                    val = jax.device_put(val, dev)
                    if _metrics.ENABLED:
                        _metrics.DEVICE_PUTS.inc()
                        _metrics.TRANSFER_BYTES.inc(
                            getattr(val, "nbytes", 0))
                self.arg_dict[k]._set_data(val)
            else:
                raise MXNetError(f"unknown forward argument {k}")
        arg_vals = {k: v._data for k, v in self.arg_dict.items()}
        aux_vals = {k: v._data for k, v in self.aux_dict.items()}
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            axis = self._mesh.axis_names[0]
            shard = NamedSharding(self._mesh, P(axis))
            repl = NamedSharding(self._mesh, P())
            # these sharded/replicated copies outlive the call — they sit
            # in self._snapshot until the next forward (a model-plus-aux
            # block of HBM), so the ledger must see them
            arg_vals = {k: _memory.register(
                jax.device_put(v, shard if k in self._data_shard_args
                               and v.ndim >= 1 else repl), tag="executor")
                        for k, v in arg_vals.items()}
            aux_vals = {k: _memory.register(jax.device_put(v, repl),
                                            tag="executor")
                        for k, v in aux_vals.items()}
        return arg_vals, aux_vals, _random.next_key()

    def forward(self, is_train: bool = False, **kwargs) -> List[NDArray]:
        arg_vals, aux_vals, key = self._gather(kwargs)
        self._snapshot = (arg_vals, aux_vals, key)
        self._pending_grads = None
        if self.group2ctx:
            return self._forward_placed(arg_vals, aux_vals, key, is_train)
        if is_train and self._grad_names and self._seen_backward:
            # training forward on an executor that trains: run the fused
            # fwd+vjp program now and hold the grads — forward();
            # backward() costs ONE compiled step, not a forward plus a
            # recomputing vjp.  Until the first backward() the vjp is
            # deferred (lazy path below): a forward-only train-mode call
            # costs one forward, and the first backward() replays the
            # fused program from the snapshot (same RNG key → same
            # dropout mask; aux restored → stats not double-updated).
            ograds = [None] * len(self._plan.out_refs)
            if _metrics.ENABLED:
                _metrics.XLA_LAUNCHES.inc(kind="fwd_bwd")
            fwd_bwd = self._fwd_bwd
            with trace_span("forward_backward", cat="executor"), \
                    _memory.oom_guard("executor.forward_backward"):
                outs, new_aux, grads, rsp_grads = fwd_bwd(
                    arg_vals, aux_vals, key, ograds)
            nk = ("fwd_bwd", self._plan_key)
            if _introspect.ENABLED and nk not in self._noted:
                self._noted.add(nk)
                _introspect.note_jit("executor:fwd_bwd", fwd_bwd,
                                     arg_vals, aux_vals, key, ograds)
            self._set_results(outs, new_aux)
            self._pending_grads = (grads, rsp_grads)
            return self._outputs_cache
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="fwd")
        fwd = self._fwd
        with trace_span("forward", cat="executor"), \
                _memory.oom_guard("executor.forward"):
            outs, new_aux = fwd(arg_vals, aux_vals, key, is_train)
        nk = ("fwd", self._plan_key)
        if _introspect.ENABLED and nk not in self._noted:
            # Executor-bind chokepoint (ISSUE 13): once per compiled
            # program, note the forward's analytical cost (a retrace,
            # no XLA compile — and no dispatch, so the perf_smoke
            # gates are unaffected)
            self._noted.add(nk)
            _introspect.note_jit("executor:fwd", fwd, arg_vals,
                                 aux_vals, key, is_train)
        self._set_results(outs, new_aux)
        return self._outputs_cache

    def backward(self, out_grads=None, is_train: bool = True) -> None:
        """Gradient pass.  Deposits the grads computed by a train-mode
        forward(); with custom head gradients it re-runs the compiled vjp
        on the forward snapshot (same RNG key → same dropout mask; aux
        values restored → moving stats not double-updated)."""
        if self._snapshot is None:
            raise MXNetError("backward called before forward")
        self._seen_backward = True
        if out_grads is None and self._pending_grads is not None:
            self._deposit_grads(*self._pending_grads)
            self._pending_grads = None
            return
        arg_vals, aux_vals, key = self._snapshot
        # replay: outputs/aux were already set by forward() — don't set
        # them again (a Monitor would record every output stat twice)
        self._run_fused(arg_vals, aux_vals, key, out_grads,
                        set_results=False)

    def forward_backward(self, out_grads=None, **kwargs) -> List[NDArray]:
        """Fused training step: outputs + grads + aux in ONE compiled call
        (the Module.fit hot path)."""
        arg_vals, aux_vals, key = self._gather(kwargs)
        self._snapshot = (arg_vals, aux_vals, key)
        self._pending_grads = None
        self._run_fused(arg_vals, aux_vals, key, out_grads)
        return self._outputs_cache

    def _run_fused(self, arg_vals, aux_vals, key, out_grads,
                   set_results=True):
        if out_grads is None:
            ograds = [None] * len(self._plan.out_refs)
        elif isinstance(out_grads, NDArray):
            ograds = [out_grads._data]
        else:
            ograds = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        if _metrics.ENABLED:
            _metrics.XLA_LAUNCHES.inc(kind="fwd_bwd")
        # OOM post-mortem chokepoint: a RESOURCE_EXHAUSTED out of the
        # fused training program dumps ledger+ring and re-raises typed;
        # the memory.oom chaos site injects a synthetic one here
        fwd_bwd = self._fwd_bwd
        with trace_span("forward_backward", cat="executor"), \
                _memory.oom_guard("executor.forward_backward"):
            _fi_fire("memory.oom", at="executor")
            outs, new_aux, grads, rsp_grads = fwd_bwd(
                arg_vals, aux_vals, key, ograds)
        nk = ("fwd_bwd", self._plan_key)
        if _introspect.ENABLED and nk not in self._noted:
            self._noted.add(nk)
            _introspect.note_jit("executor:fwd_bwd", fwd_bwd,
                                 arg_vals, aux_vals, key, ograds)
        if set_results:
            self._set_results(outs, new_aux)
        self._deposit_grads(grads, rsp_grads)

    def _deposit_grads(self, grads, rsp_grads=None):
        from .ndarray.sparse import RowSparseNDArray
        for name, (ids, vals) in (rsp_grads or {}).items():
            tgt = self.grad_dict.get(name)
            if tgt is None:
                continue
            if isinstance(tgt, RowSparseNDArray):
                # rows-only deposit; duplicate token rows segment-sum in
                # the constructor's dedup (grad_req 'write')
                tgt._assign_rows(ids, vals.astype(tgt.dtype))
            else:
                # caller bound a dense grad buffer: honor it (dense
                # scatter at the boundary, still no dense grad in-graph)
                tgt._set_data(jnp.zeros(tgt.shape, tgt.dtype).at[ids].add(
                    vals.astype(tgt.dtype)))
        for name in self._grad_names:
            if rsp_grads and name in rsp_grads:
                continue
            g = grads[name]
            tgt = self.grad_dict.get(name)
            if tgt is None:
                continue
            if self.grad_req.get(name) == "add":
                tgt._set_data(tgt._data + g.astype(tgt.dtype))
            else:
                tgt._set_data(g.astype(tgt.dtype))

    def memory_analysis(self, train: bool = True) -> dict:
        """XLA buffer-assignment footprint of this executor's compiled
        program, in bytes.  TPU redesign of the reference's allocation
        planner/estimator (GraphExecutor::InitDataEntryMemory,
        src/executor/graph_executor.cc; demoed by example/memcost): the
        inplace/sharing plan the reference computes on its own graph is
        made here by XLA's buffer assignment, so the numbers come from
        the compiler that actually allocates.  `temp` is the transient
        activation/workspace pool (what remat shrinks), `argument` the
        bound params+inputs, `peak` the high-water mark."""
        arg_vals = {k: v._data for k, v in self.arg_dict.items()}
        aux_vals = {k: v._data for k, v in self.aux_dict.items()}
        # fixed key: only shapes/dtypes matter for lowering, and a
        # diagnostic must not advance the global RNG stream
        key = jax.random.PRNGKey(0)
        if train and self._grad_names:
            ograds = [None] * len(self._plan.out_refs)
            lowered = self._fwd_bwd.lower(arg_vals, aux_vals, key, ograds)
        else:
            lowered = self._fwd.lower(arg_vals, aux_vals, key, train)
        compiled = lowered.compile()
        # one structured shape for EVERY jax version (memory.
        # compiled_stats_dict inside introspect.note_program): same
        # keys whether or not the stats carry peak_memory_in_bytes
        # (jax < 0.5 estimates it as the live-buffer sum and flags
        # peak_estimated); {} only when the backend reports nothing
        # (older PJRT).  note_program is the ONE compiled-stats surface
        # (ISSUE 13): it files the result under the HBM ledger's
        # "executor" entry (report()["compiled"]) AND the program
        # registry (snapshot()["programs"]) in the same call.
        if _introspect.ENABLED:
            return _introspect.note_program(
                "executor", compiled=compiled).get("memory", {})
        out = _memory.compiled_stats_dict(compiled.memory_analysis())
        _memory.note_compiled("executor", out)
        return out

    @property
    def outputs(self) -> List[NDArray]:
        if self._outputs_cache is None:
            raise MXNetError("call forward() first")
        return self._outputs_cache

    def _set_results(self, outs, new_aux):
        # HBM ledger: the executor HOLDS its outputs until the next
        # forward — attributable memory, not transient (sparse re-wraps
        # stay inside the scope: cast_storage builds NEW wrappers that
        # would otherwise register untagged while the tagged ones die)
        with _memory.memory_scope("output"):
            self._outputs_cache = [NDArray(o, self._ctx) for o in outs]
            stypes = self._plan.out_stypes()
            if any(s != "default" for s in stypes):
                from .ndarray.sparse import cast_storage as _cast
                self._outputs_cache = [
                    _cast(o, st) if st != "default" else o
                    for o, st in zip(self._outputs_cache, stypes)]
        for k, v in new_aux.items():
            if k in self.aux_dict:
                self.aux_dict[k]._set_data(v)
        if self._monitor is not None:
            if self._monitor_all:
                # monitor_all taps inputs too (parity: MonitorExecution
                # monitor_all records both op inputs and outputs; the
                # fused-graph analog is the bound argument set)
                for name, arr in self.arg_dict.items():
                    self._monitor(name + "_input", arr)
                    if _metrics.ENABLED:
                        _metrics.MONITOR_STATS.inc(io="input")
            names = self._plan.symbol.list_outputs()
            for i, o in enumerate(self._outputs_cache):
                self._monitor(names[i], o)
                if _metrics.ENABLED:
                    _metrics.MONITOR_STATS.inc(io="output")

    def _forward_placed(self, arg_vals, aux_vals, key, is_train):
        """group2ctx model parallelism: eager per-node execution with
        device placement by ctx_group attr (PlaceDevice-pass analog)."""
        from .ops.registry import apply_op
        plan = self._plan
        devmap = {g: (c if isinstance(c, Context) else Context(c)).jax_device()
                  for g, c in (self.group2ctx or {}).items()}
        values = [None] * len(plan.steps)
        new_aux = dict(aux_vals)

        def resolve(ref):
            if ref[0] == "var":
                return arg_vals.get(ref[1], new_aux.get(ref[1]))
            si, oi = ref[1]
            return values[si][oi]

        for si, step in enumerate(plan.steps):
            ins = [resolve(r) for r in step.in_refs]
            grp = step.node.attrs.get("ctx_group")
            if grp and grp in devmap:
                # eager D2D hop of values already attributed at their
                # creation (group2ctx placement, not a new allocation)
                ins = [jax.device_put(x, devmap[grp]) for x in ins]  # graft-lint: disable=memory-hygiene
            p = dict(step.params)
            if step.op.takes_is_train:
                p["__is_train__"] = is_train
            if step.op.needs_rng:
                ins.append(jax.random.fold_in(key, si))
            out = apply_op(step.op, tuple(sorted(p.items())), ins)
            n_vis = len(out) - len(step.op.aux_inputs)
            values[si] = out[:n_vis]
            for pos, nm in step.aux_var_names.items():
                new_aux[nm] = out[n_vis + pos]
        outs = [resolve(r) for r in plan.out_refs]
        self._set_results(outs, new_aux)
        return self._outputs_cache

    # -- utilities ----------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params: bool = False) -> None:
        def _assign(tgt: NDArray, v):
            if v._data is tgt._data:
                # pointer-handoff roundtrip (fit()'s per-epoch
                # get_params/set_params): already the same buffer
                return
            # preserve the target's sharding (mesh-replicated stay replicated)
            sh = getattr(tgt._data, "sharding", None)
            data = v._data.astype(tgt.dtype)
            if sh is not None and getattr(data, "sharding", None) != sh:
                data = jax.device_put(data, sh)
            tgt._set_data(data)

        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                _assign(self.arg_dict[k], v)
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {k}")
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                _assign(self.aux_dict[k], v)
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {k}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes (XLA caches per-shape executables —
        the bucketing memory-sharing analog)."""
        from . import ndarray as nd
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, shp in zip(self._plan.arg_names, arg_shapes):
            cur = self.arg_dict[name]
            new_args[name] = cur if tuple(cur.shape) == tuple(shp) else \
                nd.zeros(shp, ctx=self._ctx, dtype=cur.dtype)
        new_aux = {}
        for name, shp in zip(self._plan.aux_names, aux_shapes):
            cur = self.aux_dict[name]
            new_aux[name] = cur if tuple(cur.shape) == tuple(shp) else \
                nd.zeros(shp, ctx=self._ctx, dtype=cur.dtype)
        grads = {n: nd.zeros(new_args[n].shape, ctx=self._ctx)
                 for n in self._grad_names}
        return Executor(self._symbol, self._ctx, new_args, grads, self.grad_req,
                        new_aux, group2ctx=self.group2ctx, shared_exec=self)

    def set_monitor_callback(self, callback, monitor_all=False) -> None:
        self._monitor = callback
        self._monitor_all = bool(monitor_all)

    @property
    def output_dict(self):
        return dict(zip(self._plan.symbol.list_outputs(), self.outputs))

    def debug_str(self):
        return self._symbol.debug_str()
