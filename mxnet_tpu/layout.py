"""Internal compute layout for spatial ops (SURVEY.md §7: NCHW→NHWC).

User-facing semantics stay NCHW everywhere (reference parity:
src/operator/nn/convolution.cc defaults; every symbol/gluon shape
contract in this package is channels-first).  When the internal layout
is NHWC, Convolution/Deconvolution/Pooling/BatchNorm transpose
activations to channels-last at their boundaries and run the
MXU/VPU-native channels-last form: the TPU's (8, 128) vector tiles want
the contiguous minor dimension to be the channel axis, and XLA's conv
emitter tiles NHWC convs onto the MXU without the internal
transpose-pairs it inserts around NCHW ones.

Adjacent boundary transposes cancel in XLA's algebraic simplifier
(transpose∘transpose = id, and transposes commute through elementwise
ops), so a conv→BN→relu→conv chain stays channels-last end to end; only
the graph's true entry/exit pay a real data movement.

Default off (NCHW) until the on-chip A/B (experiments/layout_probe.py,
harvested by tools/chip_window.py) records a win; select with
``mxnet_tpu.layout.set_conv_layout("NHWC")`` or
``MXNET_TPU_CONV_LAYOUT=NHWC``.  Flip the flag BEFORE building
executors/CachedOps — compiled plans trace the flag at build time.
"""
from __future__ import annotations

import os

from .base import MXNetError

_VALID = ("NCHW", "NHWC")
_LAYOUT = os.environ.get("MXNET_TPU_CONV_LAYOUT", "NCHW").upper()
if _LAYOUT not in _VALID:
    raise MXNetError(
        f"MXNET_TPU_CONV_LAYOUT must be one of {_VALID}, got {_LAYOUT}")


def conv_layout() -> str:
    """The internal spatial-op layout ('NCHW' or 'NHWC' = channels-last)."""
    return _LAYOUT


def set_conv_layout(layout: str) -> str:
    """Set the internal layout; returns the previous value.  Affects ops
    traced AFTER the call — rebuild executors/CachedOps when flipping."""
    global _LAYOUT
    layout = layout.upper()
    if layout not in _VALID:
        raise MXNetError(f"layout must be one of {_VALID}, got {layout}")
    prev, _LAYOUT = _LAYOUT, layout
    return prev


def channels_last() -> bool:
    return _LAYOUT == "NHWC"


def whole_graph() -> bool:
    """Whether NHWC mode uses the GraphPlan-level propagation pass
    (transposes only at true graph edges — VERDICT r4 #1b) instead of
    per-op boundary transposes.  Default on; MXNET_TPU_CL_WHOLEGRAPH=0
    pins the old per-op mode for A/B runs."""
    return os.environ.get("MXNET_TPU_CL_WHOLEGRAPH", "1") != "0"


def to_cl(x):
    """NC[spatial] → N[spatial]C (no-op for rank<3)."""
    if x.ndim < 3:
        return x
    return x.transpose((0,) + tuple(range(2, x.ndim)) + (1,))


def from_cl(x):
    """N[spatial]C → NC[spatial] (no-op for rank<3)."""
    if x.ndim < 3:
        return x
    return x.transpose((0, x.ndim - 1) + tuple(range(1, x.ndim - 1)))
